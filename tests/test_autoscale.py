"""SLO-controller suite (tentpole: inference/autoscale.py + the
router's elasticity surface).

Layers:
  1. elasticity units — add_replica/retire_replica mechanics, the
     retired state being terminal and undispatchable, retiring a BUSY
     replica draining token-losslessly onto survivors, the tightened-
     admission gate shedding exactly the batch class;
  2. the control loop — a seeded burst drives scale-up (queue pressure
     + windowed p99 over budget), sustained idle drives retire back to
     min_replicas, and the hysteretic tighten/relax admission cycle;
  3. the acceptance gates — controller OFF is token-bit-identical to a
     never-triggering controller ON; scale-up compiles ZERO new
     programs (replicas share one InferenceEngine; CompileWatch(0));
     the chaos suite stays green with the controller active; and every
     decision is reconstructable from the exported trace with the
     metric values that triggered it (tools/trace_analyze.py fleet).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.autoscale import SLOController
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import RETIRED, ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.telemetry import Telemetry
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault
from tools.trace_analyze import analyze_fleet_trace

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_srv(eng, telemetry=None, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return ServingEngine(eng, telemetry=telemetry, **defaults)


def mk_reqs(prompts, n=6, **kw):
    return [ServeRequest(rid=i, prompt=p, max_new_tokens=n, **kw)
            for i, p in enumerate(prompts)]


# ---------------------------------------------------------------------------
# elasticity units (no controller)
# ---------------------------------------------------------------------------

def test_add_and_retire_replica_mechanics(eng):
    router = ReplicaRouter([mk_srv(eng)],
                           replica_factory=lambda i, tag: mk_srv(eng))
    assert router.add_replica(now=1.0, reason="test") == 1
    assert router.health() == ["healthy", "healthy"]
    assert router.stats["scale_ups"] == 1
    # retire drains (nothing in flight here) and parks the replica
    assert router.retire_replica(1, now=2.0) == 0
    assert router.health() == ["healthy", RETIRED]
    assert router.stats["retires"] == 1
    # retired is terminal: not re-retirable, never dispatched to
    with pytest.raises(ValueError, match="already retired"):
        router.retire_replica(1)
    with pytest.raises(ValueError, match="last dispatchable"):
        router.retire_replica(0)
    p, = prompts_of((6,))
    router.submit(ServeRequest(rid="x", prompt=p, max_new_tokens=4))
    assert len(router.replicas[1].srv.queue) == 0 \
        and all(s is None for s in router.replicas[1].srv.slots)
    # no factory and no engine => explicit error
    bare = ReplicaRouter([mk_srv(eng)])
    with pytest.raises(RuntimeError, match="replica_factory"):
        bare.add_replica()
    # an explicit engine works without a factory
    assert bare.add_replica(srv=mk_srv(eng)) == 1


def test_retire_busy_replica_token_parity(eng):
    """Retiring a replica mid-decode drains its snapshot onto the
    survivor through the breaker-drain path: every request's final
    tokens are identical to an undisturbed solo run."""
    prompts = prompts_of((6, 9, 12, 5), seed=4)
    refs = _solo_refs(eng, prompts, 6)
    router = ReplicaRouter([mk_srv(eng), mk_srv(eng)])
    for r in mk_reqs(prompts, n=6):
        router.submit(r, now=0.0)
    for _ in range(3):                       # both replicas mid-flight
        router.step()
    assert router.replicas[1].srv.busy
    drained = router.retire_replica(1, now=3.0, reason="scale-down")
    assert drained > 0
    out = router.run()
    assert sorted(out) == [0, 1, 2, 3]
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert router.health()[1] == RETIRED
    assert router.stats["drained_requests"] == drained


def test_tightened_admission_sheds_batch_class_only(eng):
    """The shed_batch gate (the controller's admission actuator) sheds
    exactly priority="batch" traffic, terminally and observably;
    interactive traffic still dispatches."""
    p1, p2 = prompts_of((6, 7), seed=2)
    tel = Telemetry()
    router = ReplicaRouter([mk_srv(eng, telemetry=tel)], telemetry=tel)
    router.shed_batch = True
    batch = ServeRequest(rid="b", prompt=p1, max_new_tokens=4,
                         priority="batch")
    inter = ServeRequest(rid="i", prompt=p2, max_new_tokens=4,
                         priority="interactive")
    assert router.submit(batch, now=1.0) is False
    assert batch.state == "shed" and batch.finished_at == 1.0
    assert router.submit(inter, now=1.0) is True
    out = router.run()
    assert set(out) == {"b", "i"}
    assert len(out["b"]) == len(p1)          # prompt only, nothing new
    assert router.stats["shed"] == 1
    sheds = [r for r in tel.tracer.records() if r[1] == "shed"]
    assert len(sheds) == 1 and sheds[0][5]["priority"] == "batch"
    # gate open again: batch admits normally
    router.shed_batch = False
    b2 = ServeRequest(rid="b2", prompt=p1.copy(), max_new_tokens=4,
                      priority="batch")
    assert router.submit(b2, now=2.0) is True


def test_fleet_snapshot_and_merged_prometheus(eng):
    """fleet_snapshot / to_prometheus merge every registry in the fleet
    (router + per-replica telemetry) into one view with the fleet shape
    and by-state gauges attached."""
    tel_a, tel_b = Telemetry(), Telemetry()   # distinct registries
    router = ReplicaRouter([mk_srv(eng, telemetry=tel_a),
                            mk_srv(eng, telemetry=tel_b)],
                           telemetry=tel_a)
    prompts = prompts_of((6, 7, 8), seed=3)
    for r in mk_reqs(prompts, n=4):
        router.submit(r, now=0.0)
    router.run()
    assert len(router.fleet_registries()) == 2    # tel_a shared, tel_b
    snap = router.fleet_snapshot()
    assert snap["fleet"]["replicas"] == 2
    assert snap["fleet"]["by_state"]["healthy"] == 2
    assert snap["counters"]["serving_completed"] == 3   # summed fleet-wide
    assert snap["counters"]["router_dispatched"] == 3
    assert snap["histograms"]["serving_ttft"]["count"] == 3
    prom = router.to_prometheus()
    assert "router_replicas_healthy 2" in prom
    assert "serving_ttft_bucket" in prom and "router_dispatched 3" in prom


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------

def test_controller_scales_up_under_burst(eng):
    """A burst the single replica cannot absorb trips the controller
    (queue pressure + windowed p99): the fleet grows via the factory,
    every decision lands in the log with its triggering metrics, and
    all tokens still complete."""
    tel = Telemetry()
    ctrl = SLOController(ttft_slo=2.0, window=8.0, eval_every=1,
                         cooldown=2.0, max_replicas=3, min_samples=2,
                         queue_high=1.5, idle_to_retire=1e9)
    router = ReplicaRouter([mk_srv(eng, telemetry=tel)],
                           replica_factory=lambda i, tag:
                               mk_srv(eng, telemetry=tel),
                           telemetry=tel, autoscale=ctrl)
    prompts = prompts_of((6, 8, 10, 7, 9, 6, 8, 11), seed=5)
    out = router.run(mk_reqs(prompts, n=6))
    assert sorted(out) == list(range(8))
    ups = [d for d in ctrl.decisions if d["action"] == "scale_up"]
    assert len(ups) == 2 and len(router.replicas) == 3
    assert router.health() == ["healthy"] * 3
    # each decision carries the metrics that triggered it
    for d in ups:
        assert d["queue_pressure"] or d["p99_ttft"] > 2.0
        assert {"p99_ttft", "window_count", "queue_depth", "load",
                "active_replicas", "at", "replica"} <= set(d)
    # registry-backed decision counters match the log
    snap = router.fleet_snapshot()
    assert snap["counters"]["autoscale_scale_ups"] == 2
    assert snap["counters"]["autoscale_decisions"] == len(ctrl.decisions)
    assert snap["counters"]["router_scale_ups"] == 2
    assert snap["gauges"]["autoscale_target_replicas"] == 3
    # cooldown held: fleet-shape changes are >= cooldown apart
    assert ups[1]["at"] - ups[0]["at"] >= 2.0


def test_controller_retires_on_sustained_idle(eng):
    """A quiet fleet above min_replicas shrinks: after idle_to_retire
    consecutive idle clock units the controller drains-and-retires the
    highest-index active replica, down to min_replicas."""
    ctrl = SLOController(ttft_slo=100.0, window=4.0, eval_every=1,
                         cooldown=1.0, min_replicas=1, max_replicas=3,
                         idle_to_retire=5.0, min_samples=2)
    router = ReplicaRouter([mk_srv(eng) for _ in range(3)],
                           autoscale=ctrl)
    prompts = prompts_of((6, 7), seed=6)
    out = router.run(mk_reqs(prompts, n=4))
    assert sorted(out) == [0, 1]
    for t in range(20):                       # idle ticks
        router.step(float(100 + t))
    retires = [d for d in ctrl.decisions if d["action"] == "retire"]
    assert [d["replica"] for d in retires] == [2, 1]   # top-down
    assert router.health() == ["healthy", RETIRED, RETIRED]
    assert router.stats["retires"] == 2
    # the floor holds: replica 0 is never retired
    assert all(d["action"] != "retire" or d["replica"] != 0
               for d in ctrl.decisions)


def test_controller_tighten_relax_hysteresis(eng):
    """With the fleet already at max_replicas the controller's only
    lever is admission: sustained pressure closes the shed_batch gate,
    and it re-opens only after the window falls below relax_ratio*slo
    (or drains entirely) — the hysteresis cycle, observable in the
    decision log and the admission gauge."""
    tel = Telemetry()
    ctrl = SLOController(ttft_slo=1.0, window=6.0, eval_every=1,
                         max_replicas=1, min_samples=1, relax_ratio=0.5,
                         queue_high=0.5, idle_to_retire=1e9)
    router = ReplicaRouter([mk_srv(eng, telemetry=tel)],
                           telemetry=tel, autoscale=ctrl)   # no factory
    prompts = prompts_of((8, 9, 10, 7), seed=7)
    out = router.run(mk_reqs(prompts, n=6))
    assert sorted(out) == [0, 1, 2, 3]
    actions = [d["action"] for d in ctrl.decisions]
    assert "tighten" in actions and "scale_up" not in actions
    assert router.shed_batch is True          # still tight at drain
    # quiet ticks past the window: the gate relaxes
    for t in range(12):
        router.step(float(200 + t))
    assert router.shed_batch is False
    ti, ri = actions.index("tighten"), \
        [d["action"] for d in ctrl.decisions].index("relax")
    assert ri > ti
    assert router.metrics.gauge("autoscale_admission_tight").value == 0
    # while tight, a batch submit would have shed (the gate is live)
    assert ctrl.decisions[ti]["shed_batch"] is True


# ---------------------------------------------------------------------------
# acceptance gates
# ---------------------------------------------------------------------------

def test_controller_off_is_bit_reference(eng):
    """autoscale=None (default) and a controller that never triggers
    produce token-bit-identical output — the controller only observes
    until a threshold crosses."""
    prompts = prompts_of((6, 9, 12, 5), seed=8)
    refs = _solo_refs(eng, prompts, 6)

    def run(ctrl):
        router = ReplicaRouter([mk_srv(eng), mk_srv(eng)],
                               autoscale=ctrl)
        return router.run(mk_reqs(prompts, n=6)), router
    out_off, r_off = run(None)
    out_on, r_on = run(SLOController(ttft_slo=1e9, idle_to_retire=1e9))
    assert sorted(out_off) == sorted(out_on) == [0, 1, 2, 3]
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out_off[i], ref)
        np.testing.assert_array_equal(out_on[i], ref)
    assert r_on.health() == r_off.health() == ["healthy", "healthy"]
    assert all(d["action"] == "noop"
               for d in r_on.autoscale.decisions)


def test_scale_up_compiles_nothing(eng):
    """The compile contract under elasticity: controller-driven
    scale-ups produce replicas sharing the fleet's InferenceEngine, so
    the whole burst-and-grow run executes under CompileWatch(0)."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    prompts = prompts_of((6, 8, 10, 7, 9, 6), seed=9)
    # warm the slot programs outside the watch
    mk_srv(eng).run(mk_reqs(prompts[:1], n=4))
    ctrl = SLOController(ttft_slo=2.0, window=8.0, eval_every=1,
                         cooldown=2.0, max_replicas=3, min_samples=2,
                         queue_high=1.0, idle_to_retire=1e9)
    router = ReplicaRouter([mk_srv(eng)],
                           replica_factory=lambda i, tag: mk_srv(eng),
                           autoscale=ctrl)
    watch = CompileWatch(max_compiles=0, label="autoscale")
    watch.wrap(eng._prefill_slot)
    watch.wrap(eng._decode_slots)
    with watch:                               # raises on any compile
        out = router.run(mk_reqs(prompts, n=6))
    assert sorted(out) == list(range(6))
    assert router.stats["scale_ups"] >= 1     # the fleet actually grew


@pytest.mark.slow
def test_chaos_green_with_controller_active(eng):
    """The router chaos scenario (breaker trips + drains under seeded
    router.step faults) stays token-lossless with the controller
    ticking: breaks, drains, scale-ups and admission all compose."""
    prompts = prompts_of((6, 9, 12, 5, 8, 10), seed=10)
    refs = _solo_refs(eng, prompts, 6)
    chaos = [Fault("router.step", "device_error", step=4, count=3)]
    with faults_lib.injected(*chaos, seed=0) as inj:
        ctrl = SLOController(ttft_slo=2.0, window=8.0, eval_every=1,
                             cooldown=2.0, max_replicas=4, min_samples=2,
                             queue_high=1.5, idle_to_retire=1e9)
        router = ReplicaRouter([mk_srv(eng), mk_srv(eng)],
                               replica_factory=lambda i, tag: mk_srv(eng),
                               autoscale=ctrl, breaker_threshold=2)
        out = router.run(mk_reqs(prompts, n=6))
    assert inj.fired                          # the chaos happened
    assert sorted(out) == list(range(6))
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    # breaker state and controller decisions coexist in the stats
    assert router.stats["breaker_trips"] >= 1
    assert len(ctrl.decisions) > 0


def test_decisions_reconstructable_from_trace(eng, tmp_path):
    """The observability acceptance gate: every controller evaluation
    lands in the Perfetto export as an ``autoscale`` instant carrying
    the triggering metric values, and ``trace_analyze fleet`` rebuilds
    the full decision + fleet-shape timeline from the file alone."""
    tel = Telemetry()
    ctrl = SLOController(ttft_slo=2.0, window=8.0, eval_every=1,
                         cooldown=2.0, max_replicas=3, min_samples=2,
                         queue_high=1.5, idle_to_retire=1e9)
    router = ReplicaRouter([mk_srv(eng, telemetry=tel)],
                           replica_factory=lambda i, tag:
                               mk_srv(eng, telemetry=tel),
                           telemetry=tel, autoscale=ctrl)
    prompts = prompts_of((6, 8, 10, 7, 9, 6, 8, 11), seed=11)
    router.run(mk_reqs(prompts, n=6))
    path = tel.export_trace(str(tmp_path / "fleet.json"))
    summary = analyze_fleet_trace(path, quiet=True)
    traced = summary["autoscale"]["decisions"]
    assert len(traced) == len(ctrl.decisions)
    for got, want in zip(traced, ctrl.decisions):
        assert got["action"] == want["action"]
        assert got["p99_ttft"] == want["p99_ttft"]
        assert got["queue_depth"] == want["queue_depth"]
        assert got["active_replicas"] == want["active_replicas"]
    ups = summary["autoscale"]["by_action"].get("scale_up", 0)
    assert ups == router.stats["scale_ups"] >= 1
    # the fleet-shape timeline matches: one 'scale add' per scale-up
    adds = [s for s in summary["scale"] if s["action"] == "add"]
    assert [a["replica"] for a in adds] \
        == list(range(1, 1 + ups))
    assert summary["dispatch"]["total"] == len(prompts)
