"""BERT family + fused encoder layer tests (ref:
tests/unit/test_cuda_forward.py kernel-parity-vs-python-BERT pattern;
tests/unit/modeling.py post-LN, modelingpreln.py pre-LN variants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import bert
from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig, init_layer_params, layer_forward,
    layer_forward_reference)

TINY = dict(vocab_size=97, n_layers=2, n_heads=2, d_model=32,
            max_seq_len=32, dropout=0.0)


def _mlm_batch(rng, B=4, S=16, vocab=97, mask_frac=0.3):
    toks = rng.integers(0, vocab, (B, S)).astype(np.int32)
    labels = np.full((B, S), -1, np.int32)
    mask = rng.random((B, S)) < mask_frac
    labels[mask] = toks[mask]
    inp = toks.copy()
    inp[mask] = 0  # [MASK]
    return {"tokens": inp, "mlm_labels": labels}


# ------------------------------------------------------- encoder layer

@pytest.mark.parametrize("pre_ln", [True, False])
def test_layer_parity_vs_fp32_reference(rng, pre_ln):
    """bf16 fused layer vs fp32 naive math within tolerance (ref:
    test_cuda_forward.py tolerances: rtol in the 1e-2 range for fp16)."""
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4,
                                     pre_layer_norm=pre_ln,
                                     attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0)
    params = init_layer_params(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(rng.standard_normal((2, 16, 64)), jnp.float32)
    ref = layer_forward_reference(params, x, cfg)
    p16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), params)
    out = layer_forward(p16, x.astype(jnp.bfloat16), cfg)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 5e-2, err


def test_layer_padding_mask(rng):
    """Padding tokens must not influence unpadded positions."""
    cfg = DeepSpeedTransformerConfig(hidden_size=32, heads=2,
                                     attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0)
    params = init_layer_params(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(rng.standard_normal((1, 8, 32)), jnp.float32)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    out1 = layer_forward(params, x, cfg, attn_mask=mask)
    # changing padded content must not change valid positions
    x2 = x.at[:, 4:].set(123.0)
    out2 = layer_forward(params, x2, cfg, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(out1[:, :4]),
                               np.asarray(out2[:, :4]), atol=1e-5)


def test_layer_flash_path_matches_jnp(rng):
    """Unmasked long-seq layer (flash-eligible) vs masked-with-all-ones
    (jnp path) — same math, two kernels."""
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=2,
                                     attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0)
    params = init_layer_params(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(rng.standard_normal((2, 128, 64)), jnp.float32)
    flash_out = layer_forward(params, x, cfg)  # attn_mask None
    ones = jnp.ones((2, 128), jnp.int32)
    jnp_out = layer_forward(params, x, cfg, attn_mask=ones)
    err = float(jnp.max(jnp.abs(flash_out - jnp_out)))
    assert err < 2e-2, err


# --------------------------------------------------------------- model

def test_bert_forward_shapes(rng):
    cfg = bert.BertConfig(**TINY)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.asarray(rng.integers(0, 97, (2, 16)), jnp.int32)
    mlm, nsp = bert.forward(params, toks, cfg)
    assert mlm.shape == (2, 16, 97)
    assert nsp.shape == (2, 2)


def test_bert_presets():
    large = bert.preset("bert-large")
    assert large.n_layers == 24 and large.d_model == 1024
    base = bert.preset("bert-base", max_seq_len=128)
    assert base.max_seq_len == 128
    # analytic vs real param count
    cfg = bert.BertConfig(**TINY)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    real = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert real == bert.num_params(cfg)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_bert_mlm_overfits(devices, pre_ln, rng):
    """Tiny-model convergence, both residual placements (ref:
    modeling.py vs modelingpreln.py coverage)."""
    cfg = bert.BertConfig(**{**TINY, "pre_layer_norm": pre_ln})
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    ds_cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
              "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
              "steps_per_print": 10000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=bert.make_loss_fn(cfg), model_parameters=params, config=ds_cfg)
    batch = _mlm_batch(rng, B=8, S=16)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.6, losses


def test_bert_nsp_loss(rng):
    cfg = bert.BertConfig(**TINY)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    batch = _mlm_batch(rng, B=4, S=16)
    l_mlm = bert.loss_fn(params, batch, jax.random.PRNGKey(0), cfg,
                         deterministic=True)
    batch["nsp_labels"] = jnp.asarray([0, 1, 0, 1], jnp.int32)
    l_both = bert.loss_fn(params, batch, jax.random.PRNGKey(0), cfg,
                          deterministic=True)
    assert float(l_both) > float(l_mlm)  # NSP term added


def test_bert_attention_mask_end_to_end(rng):
    cfg = bert.BertConfig(**TINY)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.asarray(rng.integers(0, 97, (2, 16)), jnp.int32)
    mask = jnp.concatenate([jnp.ones((2, 8), jnp.int32),
                            jnp.zeros((2, 8), jnp.int32)], axis=1)
    mlm1, _ = bert.forward(params, toks, cfg, attention_mask=mask)
    toks2 = toks.at[:, 8:].set(5)
    mlm2, _ = bert.forward(params, toks2, cfg, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(mlm1[:, :8], np.float32),
                               np.asarray(mlm2[:, :8], np.float32),
                               atol=1e-2)


def test_bert_tensor_parallel(devices, rng):
    """TP=2 sharded BERT matches unsharded forward loss."""
    cfg = bert.BertConfig(**TINY)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    batch = _mlm_batch(rng, B=8, S=16)
    ds_base = {"train_batch_size": 8,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
               "steps_per_print": 10000}
    e1, _, _, _ = deepspeed_tpu.initialize(
        model=bert.make_loss_fn(cfg),
        model_parameters=jax.tree_util.tree_map(np.asarray, params),
        config=dict(ds_base))
    ds_tp = dict(ds_base, mesh={"tensor_parallel_size": 2})
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=bert.make_loss_fn(cfg),
        model_parameters=jax.tree_util.tree_map(np.asarray, params),
        config=ds_tp, partition_rules=bert.bert_partition_rules())
    # qkv kernel is actually sharded over the model axis
    shard = e2.state.params["block"]["qkv"]["kernel"].sharding
    assert "model" in str(shard.spec), shard.spec
    m1 = e1.train_batch(batch)
    m2 = e2.train_batch(batch)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-2


def test_squad_finetune_converges(devices):
    """BingBertSquad analog: span head fine-tunes through the engine and
    the loss falls on a learnable synthetic span task."""
    import deepspeed_tpu
    cfg = bert.BertConfig(vocab_size=64, n_layers=2, n_heads=2, d_model=32,
                          max_seq_len=32, dtype=jnp.float32, dropout=0.0)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    params["qa"] = bert.init_squad_head(jax.random.PRNGKey(1), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=bert.make_squad_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
                "steps_per_print": 1000})
    r = np.random.default_rng(0)
    tokens = r.integers(0, 64, (8, 32)).astype(np.int32)
    # learnable: answer span marked by a sentinel token value
    tokens[:, 5] = 63
    tokens[:, 9] = 62
    batch = {"tokens": tokens,
             "start_positions": np.full((8,), 5, np.int32),
             "end_positions": np.full((8,), 9, np.int32)}
    losses = [float(eng.train_batch(batch)["loss"]) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.3, losses


def test_squad_logits_shapes(devices):
    cfg = bert.BertConfig(vocab_size=32, n_layers=1, n_heads=2, d_model=16,
                          max_seq_len=16, dtype=jnp.float32, dropout=0.0)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    params["qa"] = bert.init_squad_head(jax.random.PRNGKey(1), cfg)
    toks = np.random.default_rng(0).integers(0, 32, (2, 12)).astype(np.int32)
    s, e = bert.squad_logits(params, jnp.asarray(toks), cfg)
    assert s.shape == (2, 12) and e.shape == (2, 12)
    assert s.dtype == jnp.float32


def test_squad_ignored_positions_excluded(devices):
    """Out-of-range span positions (seq_len = unanswerable, or -1) must
    not contribute loss (reference ignored_index semantics)."""
    cfg = bert.BertConfig(vocab_size=32, n_layers=1, n_heads=2, d_model=16,
                          max_seq_len=16, dtype=jnp.float32, dropout=0.0)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    params["qa"] = bert.init_squad_head(jax.random.PRNGKey(1), cfg)
    toks = np.random.default_rng(0).integers(0, 32, (4, 12)).astype(np.int32)
    rng = jax.random.PRNGKey(0)
    base = {"tokens": toks,
            "start_positions": np.array([3, 5, 2, 7], np.int32),
            "end_positions": np.array([4, 6, 3, 8], np.int32)}
    ref = float(bert.squad_loss_fn(params, base, rng, cfg,
                                   deterministic=True))
    # appending an unanswerable example (pos = seq_len) must not change
    # the masked-mean loss over the valid ones
    ext = {"tokens": np.concatenate([toks, toks[:1]]),
           "start_positions": np.append(base["start_positions"], 12),
           "end_positions": np.append(base["end_positions"], -1)}
    got = float(bert.squad_loss_fn(params, ext, rng, cfg,
                                   deterministic=True))
    np.testing.assert_allclose(got, ref, rtol=1e-6)
