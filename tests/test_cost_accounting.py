"""Cost-accounting plane tests (tentpole: deepspeed_tpu/telemetry/
costs.py + flight.py wired through inference/serving.py and
inference/router.py; docs/OBSERVABILITY.md).

Layers:
  1. program cost registry — every registered engine twin present on
     the engine gets an entry on CPU (XLA or analytic fallback), with
     the gauges exported;
  2. conservation — sum of per-request footprints (plus the unowned
     system residue) equals the accountant's per-class totals and the
     global counters EXACTLY, as integers, across eviction/requeue,
     spec-decode fallback, the fused decode horizon N=8, and a router
     fleet draining a killed replica onto survivors;
  3. off-mode — telemetry off is bit-identical with zero recompiles
     and registers none of the cost metrics;
  4. device-time snapshot/delta regression — reusing one engine for a
     second drive must not double-bill the first drive's device time;
  5. flight recorder — the chaos-induced DegradedError writes a
     versioned, CRC-stamped artifact from which tools/postmortem.py
     reconstructs the request timeline, fired faults and per-tenant
     cost summary with ZERO live objects.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine)
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.models import gpt
from deepspeed_tpu.telemetry import Telemetry, merge_registries
from deepspeed_tpu.telemetry.costs import (NOOP_COSTS, ProgramCostRegistry,
                                           attn_flops, infer_flops,
                                           model_flops_per_token)
from deepspeed_tpu.telemetry.flight import load_artifact
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault, FaultInjector
from deepspeed_tpu.utils.jit_registry import (DISPATCH_CLASSES,
                                              engine_programs)

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def _fold(requests, *accountants):
    """Re-sum per-request footprints + each accountant's unowned
    system residue into per-class totals (the conservation LHS)."""
    tot = {c: {"flops": 0, "hbm_bytes": 0, "dispatches": 0}
           for c in DISPATCH_CLASSES}
    bs = 0
    for r in requests:
        for c in DISPATCH_CLASSES:
            for k in tot[c]:
                tot[c][k] += r.cost[c][k]
        bs += r.cost["block_seconds"]
    for acc in accountants:
        for c in DISPATCH_CLASSES:
            for k in tot[c]:
                tot[c][k] += acc.system[c][k]
        bs += acc.system["block_seconds"]
    return tot, bs


def _assert_conserved(srv):
    """Footprints + system == totals == counters, exactly."""
    folded, bs = _fold(srv.finished, srv.costs)
    for c in DISPATCH_CLASSES:
        assert folded[c] == srv.costs.totals[c], \
            f"class {c}: footprints {folded[c]} != totals " \
            f"{srv.costs.totals[c]}"
    assert bs == srv.costs.block_seconds_total
    counters = srv.metrics.snapshot()["counters"]
    assert counters["serving_flops_total"] == \
        sum(folded[c]["flops"] for c in DISPATCH_CLASSES)
    assert counters["serving_hbm_bytes_total"] == \
        sum(folded[c]["hbm_bytes"] for c in DISPATCH_CLASSES)
    assert counters["serving_kv_block_seconds"] == bs


# ---------------------------------------------------------------------------
# analytic model units
# ---------------------------------------------------------------------------

def test_analytic_formulas_are_exact_integers():
    cfg, _ = tiny()
    assert model_flops_per_token(cfg) == 2 * (
        gpt.num_params(cfg) - cfg.vocab_size * cfg.d_model
        + (cfg.d_model * cfg.vocab_size if cfg.tie_embeddings else 0))
    # attention: token at position p attends p+1 keys, 4*d flops per
    # (q, k) pair per layer — check the closed form against the loop
    n, s = 5, 7
    ref = sum(4 * cfg.n_layers * cfg.d_model * (s + i + 1)
              for i in range(n))
    assert attn_flops(cfg, n, s) == ref
    assert infer_flops(cfg, n, s) == \
        n * model_flops_per_token(cfg) + ref
    # decomposition: a chunked prefill must charge the same flops as
    # one shot — conservation across chunk boundaries
    whole = infer_flops(cfg, 12, 0)
    split = infer_flops(cfg, 8, 0) + infer_flops(cfg, 4, 8)
    assert whole == split


def test_program_cost_registry_every_twin_populated_on_cpu(eng):
    """Acceptance: every registered twin that exists on the engine gets
    a registry entry on CPU — via XLA cost analysis or the analytic
    fallback — and the per-program gauges are exported."""
    tel = Telemetry()
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        telemetry=tel)
    present = {pid for pid, attr, _ in engine_programs()
               if getattr(eng, attr, None) is not None}
    assert present, "engine exposes no registered programs?"
    assert set(srv.cost_registry.entries) == present
    assert {"prefill_slot", "decode_slots"} <= present
    for pid, entry in srv.cost_registry.entries.items():
        assert entry["source"] in ("analytic", "xla")
        assert entry["flops"] >= 0
        assert entry["bytes_accessed"] > 0
        assert entry["dispatch_class"] in DISPATCH_CLASSES
        assert srv.metrics.gauge(f"program_flops_{pid}").value >= 0
        assert srv.metrics.gauge(f"program_hbm_bytes_{pid}").value > 0
    # the snapshot is JSON round-trippable
    js = json.loads(srv.cost_registry.dumps())
    assert set(js["programs"]) == present


# ---------------------------------------------------------------------------
# conservation: footprints == totals == counters, exactly
# ---------------------------------------------------------------------------

# tier-1 runs ``-m 'not slow'`` under a hard wall-clock budget
# (ROADMAP.md); the heavier conservation workloads carry the slow mark
# and run unfiltered in the gate (tools/gate.sh full + chaos legs)

@pytest.mark.slow
def test_conservation_exact_across_evict_requeue(eng):
    """The tight-pool eviction workload: a preempted request carries
    its footprint through evict -> requeue -> re-admit, and the books
    still balance to the integer."""
    p1, p2 = prompts_of((10, 9), seed=9)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                        prefill_chunk=8, telemetry=Telemetry())
    srv.cache.watermark = 0
    srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
             ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
    assert srv.stats["evictions"] >= 1
    _assert_conserved(srv)
    # the evicted request's footprint survived the round trip: its
    # prefill charges include the re-prefill after re-admission
    victim = next(r for r in srv.finished if r.evictions > 0)
    assert victim.cost["prefill"]["dispatches"] >= 2
    assert srv.costs.totals["prefill"]["flops"] > 0
    assert srv.costs.totals["decode"]["flops"] > 0


@pytest.mark.slow
def test_conservation_spec_decode_with_fallback(eng):
    """Speculative decoding charges the verify class for the full
    chunk; injected draft faults degrade steps to plain decode — the
    books balance across the mode switches."""
    prompts = prompts_of((5, 9, 12), seed=7)
    with faults_lib.injected(
            Fault("serving.spec_draft", "device_error", step=1, count=3),
            seed=0):
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, spec_decode=True,
                            telemetry=Telemetry())
        srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8)
                 for i, p in enumerate(prompts)])
    assert srv.stats["spec_fallbacks"] >= 3
    assert srv.stats["spec_steps"] > 0
    _assert_conserved(srv)
    assert srv.costs.totals["verify"]["dispatches"] > 0
    assert srv.costs.totals["decode"]["dispatches"] > 0


@pytest.mark.slow
def test_conservation_decode_horizon_8(eng):
    """Acceptance: exact conservation holds with DS_DECODE_HORIZON=8 —
    one fused dispatch bills N tokens per slot, integrated at horizon
    boundaries."""
    prompts = prompts_of((6, 11, 4), seed=3)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        prefill_chunk=8, spec_decode=False,
                        decode_horizon=8, telemetry=Telemetry())
    srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=16)
             for i, p in enumerate(prompts)])
    _assert_conserved(srv)
    gen = sum(len(r.out) for r in srv.finished)
    d = srv.costs.totals["decode"]
    # the horizon amortization is visible in the books: far fewer
    # decode dispatches than decoded tokens...
    assert 0 < d["dispatches"] < gen
    # ...while the flops cover every token (>= one per-token matmul
    # pass per generated token; prefill emits the first token of each)
    assert d["flops"] >= (gen - len(prompts)) * model_flops_per_token(
        eng.config if hasattr(eng, "config") else srv.engine.cfg)


def test_conservation_router_drain_onto_survivors(eng):
    """A replica crash-killed mid-run drains its in-flight requests
    (footprints ride the drain snapshots) onto survivors: summing the
    final per-request footprints plus every replica's system residue
    must equal the fleet-wide per-class totals — and the merged
    registries' counters."""
    prompts = prompts_of(tuple(5 + (i % 4) * 3 for i in range(6)),
                         seed=29)
    inj = FaultInjector([Fault("router.step", "crash", step=7)], seed=0)
    fleet = [ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                           prefill_chunk=8, spec_decode=False,
                           faults=inj, telemetry=Telemetry())
             for _ in range(3)]
    router = ReplicaRouter(fleet, faults=inj)
    out = router.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8)
                      for i, p in enumerate(prompts)])
    assert inj.fired and router.stats["drained_requests"] >= 1
    assert set(out) == set(range(6))
    finished = [r for rep in router.replicas for r in rep.srv.finished]
    folded, bs = _fold(finished, *[rep.srv.costs
                                   for rep in router.replicas])
    for c in DISPATCH_CLASSES:
        fleet_tot = {k: sum(rep.srv.costs.totals[c][k]
                            for rep in router.replicas)
                     for k in folded[c]}
        assert folded[c] == fleet_tot, f"class {c} diverged across drain"
    assert bs == sum(rep.srv.costs.block_seconds_total
                     for rep in router.replicas)
    merged = merge_registries([rep.srv.metrics
                               for rep in router.replicas])
    assert merged.counter("serving_flops_total").value == \
        sum(folded[c]["flops"] for c in DISPATCH_CLASSES)
    assert merged.counter("serving_hbm_bytes_total").value == \
        sum(folded[c]["hbm_bytes"] for c in DISPATCH_CLASSES)


def test_tenant_rollup_keyed_by_adapter_id(eng):
    """Requests tagged with adapter ids roll their footprints into
    per-tenant buckets; untagged requests land in "base"; the tenant
    sums re-fold to the global totals."""
    prompts = prompts_of((6, 7, 8, 5), seed=11)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        prefill_chunk=8, telemetry=Telemetry())
    reqs = [ServeRequest(rid=i, prompt=p, max_new_tokens=4)
            for i, p in enumerate(prompts)]
    # tag without the adapter pool: attribution keys on adapter_id
    # only, the serving path treats unknown ids as base weights when
    # lora_serve is off
    reqs[1].adapter_id = None
    srv.run(reqs)
    _assert_conserved(srv)
    tenants = srv.costs.tenants
    assert "base" in tenants
    for c in DISPATCH_CLASSES:
        for k in ("flops", "hbm_bytes", "dispatches"):
            assert sum(fp[c][k] for fp in tenants.values()) == \
                srv.costs.totals[c][k]


# ---------------------------------------------------------------------------
# off-mode: bit-identity, zero compiles, zero cost metrics
# ---------------------------------------------------------------------------

def test_off_mode_bit_identical_zero_compiles_no_metrics(eng):
    """Acceptance: telemetry/recorder off is the bit-reference — same
    tokens with CompileWatch(0) armed, the accountant is the no-op
    twin, and none of the cost metrics materialize."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    prompts = prompts_of((5, 9, 12), seed=13)

    def drive(telemetry):
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, spec_decode=False,
                            telemetry=telemetry)
        out = srv.run([ServeRequest(rid=i, prompt=p.copy(),
                                    max_new_tokens=6)
                       for i, p in enumerate(prompts)])
        return srv, out

    srv_on, out_on = drive(Telemetry())          # warmup + reference
    watch = CompileWatch(max_compiles=0, label="serving+costs-off")
    watch.wrap(eng._prefill_slot)
    watch.wrap(eng._decode_slots)
    with watch:
        srv_off, out_off = drive(False)
    for rid in out_on:
        np.testing.assert_array_equal(out_off[rid], out_on[rid])
    assert srv_off.costs is NOOP_COSTS and not srv_off.costs.enabled
    assert srv_off.cost_registry is None
    assert not srv_off.flight.enabled and srv_off.flight.dump("x") is None
    for name in ("serving_flops_total", "serving_hbm_bytes_total",
                 "serving_kv_block_seconds"):
        assert name not in srv_off.metrics.names()
        assert name in srv_on.metrics.names()
    # footprints exist but stay empty off-mode (the dataclass default)
    assert all(r.cost["decode"]["dispatches"] == 0
               for r in srv_off.finished)


def test_cost_accounting_knob_without_telemetry(eng):
    """DS_COST_ACCOUNTING / the explicit ctor knob turns attribution on
    with telemetry OFF: charges land in the engine's private registry
    and the streams stay identical (host-int arithmetic only)."""
    p, = prompts_of((8,), seed=2)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        telemetry=False, cost_accounting=True)
    srv.run([ServeRequest(rid="n", prompt=p, max_new_tokens=6)])
    assert srv.costs.enabled
    _assert_conserved(srv)
    assert srv.metrics.counter("serving_flops_total").value > 0


# ---------------------------------------------------------------------------
# device-time snapshot/delta regression
# ---------------------------------------------------------------------------

def test_device_time_snapshot_delta_not_double_billed(eng):
    """``device_time_s`` accumulates for the engine's lifetime; the
    satellite fix is the snapshot/delta idiom — a second drive on the
    SAME engine must be billable as its own delta, not the running
    total (which double-bills drive one, the old infer_bench min-of-k
    bug)."""
    p, = prompts_of((8,), seed=4)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        prefill_chunk=8, spec_decode=False)
    d0 = srv.device_time_snapshot()
    assert d0 == 0.0
    srv.run([ServeRequest(rid="a", prompt=p.copy(), max_new_tokens=6)])
    d1 = srv.device_time_snapshot()
    srv.run([ServeRequest(rid="b", prompt=p.copy(), max_new_tokens=6)])
    d2 = srv.device_time_snapshot()
    assert 0 < d1 < d2                      # monotonic accumulator
    delta2 = d2 - d1
    assert delta2 > 0
    # the regression: billing drive two the running total would claim
    # strictly more device time than the drive used
    assert delta2 < d2
    assert srv.device_time_s == d2          # snapshot IS the accumulator


# ---------------------------------------------------------------------------
# flight recorder: chaos postmortem round-trip with zero live objects
# ---------------------------------------------------------------------------

def test_degraded_error_writes_postmortem_roundtrip(eng, tmp_path):
    """Acceptance: the chaos-induced watchdog DegradedError yields a
    versioned, CRC-stamped artifact from which tools/postmortem.py
    (stdlib-only — no jax, no live objects) reconstructs the request
    timeline, the fired faults, and the per-tenant cost summary."""
    outdir = str(tmp_path / "flight")
    p1, p2 = prompts_of((6, 9), seed=12)
    with faults_lib.injected(
            Fault("serving.decode", "slow", step=4, count=2, param=0.05),
            seed=0) as inj:
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            step_time_budget_s=0.01, watchdog_grace=2,
                            spec_decode=False, decode_horizon=1,
                            telemetry=Telemetry(),
                            flight_recorder=True, flight_dir=outdir)
        with pytest.raises(DegradedError, match="over budget"):
            srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                     ServeRequest(rid="b", prompt=p2, max_new_tokens=3)])
    assert srv.flight.dumps, "degrade wrote no artifact"
    path = srv.flight.dumps[-1]
    assert os.path.exists(path)

    # the reader side: tools/postmortem.py mirrors (not imports) the
    # package's verification — both must accept the artifact
    body = load_artifact(path)
    from tools.postmortem import analyze_postmortem
    from tools.postmortem import load_artifact as load_stdlib
    assert load_stdlib(path) == body
    summary = analyze_postmortem(body)
    assert summary["incident"]["reason"].startswith("degraded:")
    assert "over budget" in summary["incident"]["reason"]
    # fired faults reconstructed exactly
    assert [tuple(f) for f in summary["faults"]] == inj.fired
    # request timeline: both rids present with their lifecycle edges
    assert {"a", "b"} <= set(summary["requests"])
    for rid in ("a", "b"):
        counts = summary["requests"][rid]["event_counts"]
        assert counts.get("enqueue") == 1 and counts.get("admit", 0) >= 1
    # "b" finished before the trip; its terminal event is in the ring
    assert summary["requests"]["b"]["event_counts"].get("finish") == 1
    # per-tenant cost summary matches the live accountant to the integer
    live = srv.costs.snapshot()
    assert summary["totals"]["per_class"] == live["totals"]
    assert summary["totals"]["flops_total"] == live["flops_total"]
    assert summary["tenants"]["base"]["footprint"] == \
        live["tenants"]["base"]
    # resolved flags and the program registry made it into the artifact
    assert summary["flags"].get("DS_FLIGHT_RECORDER") is not None
    assert summary["programs"]["count"] == len(srv.cost_registry.entries)
    # identity pins the process that died
    assert body["identity"]["backend"] in ("cpu", "tpu", "gpu")

    # trace_analyze's cost subcommand reads the same artifact
    import sys
    sys.path.insert(0, ".")
    from tools.trace_analyze import analyze_cost
    cs = analyze_cost(path, quiet=True)
    assert cs["source"] == "postmortem"
    assert cs["flops_total"] == live["flops_total"]

    # resuming after the degrade still balances the books
    srv.run()
    _assert_conserved(srv)


def test_postmortem_artifact_tamper_detected(eng, tmp_path):
    """A hand-edited or truncated artifact fails CRC verification
    loudly in BOTH readers."""
    outdir = str(tmp_path / "flight")
    p, = prompts_of((6,), seed=5)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        telemetry=Telemetry(), flight_recorder=True,
                        flight_dir=outdir)
    srv.run([ServeRequest(rid="x", prompt=p, max_new_tokens=4)])
    path = srv.flight.dump("manual")
    body = load_artifact(path)               # valid as written
    assert body["reason"] == "manual"
    with open(path) as f:
        artifact = json.load(f)
    artifact["body"]["reason"] = "tampered"
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(artifact, f)
    with pytest.raises(ValueError, match="CRC"):
        load_artifact(bad)
    from tools.postmortem import load_artifact as load_stdlib
    with pytest.raises(ValueError, match="CRC"):
        load_stdlib(bad)
    # version gate: an unknown schema version is refused before CRC
    artifact["version"] = 99
    with open(bad, "w") as f:
        json.dump(artifact, f)
    with pytest.raises(ValueError, match="version"):
        load_artifact(bad)


def test_router_break_writes_fleet_postmortem(eng, tmp_path):
    """A breaker break on the fleet writes a router-labeled artifact
    bundling per-replica cost snapshots and the drain timeline."""
    outdir = str(tmp_path / "fleet_flight")
    prompts = prompts_of((5, 8, 11, 6), seed=17)
    inj = FaultInjector([Fault("router.step", "crash", step=7)], seed=0)
    fleet = [ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                           prefill_chunk=8, spec_decode=False,
                           faults=inj, telemetry=Telemetry())
             for _ in range(3)]
    router = ReplicaRouter(fleet, faults=inj, flight_recorder=True,
                           flight_dir=outdir)
    router.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8)
                for i, p in enumerate(prompts)])
    assert router.stats["breaker_trips"] >= 1
    assert router.flight.dumps
    body = load_artifact(router.flight.dumps[-1])
    assert body["label"] == "router"
    assert body["reason"].startswith("breaker:")
    assert set(body["costs"]) == {f"r{i}" for i in range(3)}
    # the drained requests' rows carry their replica of record
    assert any(row.get("replica") is not None
               for row in body["requests"])
