"""Native async I/O tests (analog of ref tests/unit/test_aio.py:335)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AlignedBuffer, AsyncIOHandle
from deepspeed_tpu.ops.op_builder import AsyncIOBuilder


@pytest.fixture(scope="module")
def aio():
    assert AsyncIOBuilder().is_compatible()
    h = AsyncIOHandle(block_size=1 << 16, thread_count=4)
    yield h
    h.close()


def test_sync_write_read_roundtrip(aio, tmp_path):
    data = np.random.default_rng(0).standard_normal(100_000).astype(np.float32)
    path = str(tmp_path / "t.bin")
    assert aio.sync_pwrite(data, path) == data.nbytes
    out = np.empty_like(data)
    assert aio.sync_pread(out, path) == data.nbytes
    np.testing.assert_array_equal(data, out)


def test_async_overlapped_ops(aio, tmp_path):
    rng = np.random.default_rng(1)
    bufs = [rng.standard_normal(50_000).astype(np.float32) for _ in range(8)]
    for i, b in enumerate(bufs):
        aio.async_pwrite(b, str(tmp_path / f"f{i}.bin"))
    assert aio.wait() == 8
    outs = [np.empty_like(b) for b in bufs]
    for i, o in enumerate(outs):
        aio.async_pread(o, str(tmp_path / f"f{i}.bin"))
    assert aio.wait() == 8
    for b, o in zip(bufs, outs):
        np.testing.assert_array_equal(b, o)


def test_offsets(aio, tmp_path):
    path = str(tmp_path / "off.bin")
    a = np.arange(1000, dtype=np.float32)
    b = np.arange(1000, 2000, dtype=np.float32)
    aio.sync_pwrite(a, path, offset=0)
    aio.sync_pwrite(b, path, offset=a.nbytes)
    out = np.empty(2000, np.float32)
    aio.sync_pread(out, path)
    np.testing.assert_array_equal(out[:1000], a)
    np.testing.assert_array_equal(out[1000:], b)


def test_aligned_buffer():
    buf = AlignedBuffer(10_000, dtype=np.float32)
    assert buf.data_ptr() % 4096 == 0
    v = buf.view(2500)
    v[:] = 1.5
    assert np.all(buf.view(2500) == 1.5)
    buf.free()


def test_read_error(aio, tmp_path):
    out = np.empty(10, np.float32)
    with pytest.raises(OSError):
        aio.sync_pread(out, str(tmp_path / "missing.bin"))
