"""Rehearsal of the unattended rig-recovery cycle (VERDICT r4 #7).

The real cycle — rig_watch polls the backend, sees two green probes,
drains chip_queue into a results log, and pick_headline --apply flips
BENCH_HEADLINE.json for an above-margin winner — has exactly one shot
per round at the real rig. These tests run the ACTUAL scripts (no
mocks, real subprocesses, real files) against the CPU backend at
second-scale timings, so a bug in the orchestration is caught here and
not discovered as a silently-missing round bench.

Reference analog: the reference's perf harness is itself exercised by
sanity-check runs before being trusted (ref:
tests/model/run_sanity_check.py:8, run_perf_baseline.py:17).
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_watch(tmp_path, env_extra, args, timeout):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "tools/rig_watch.py"] + args,
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=timeout)


def test_recovery_cycle_end_to_end(tmp_path):
    """Probe green -> queue drain -> headline flip, all through the real
    scripts on the CPU backend."""
    results = tmp_path / "results.log"
    head = tmp_path / "HEADLINE.json"
    real_head = os.path.join(ROOT, "BENCH_HEADLINE.json")
    real_before = (open(real_head).read()
                   if os.path.exists(real_head) else None)
    r = _run_watch(
        tmp_path,
        {"DS_REHEARSAL": "1",
         "DS_RIGWATCH_POLL_S": "1", "DS_RIGWATCH_CONFIRM_S": "0"},
        ["--deadline-hours", "0.05",
         "--results", str(results), "--pick-out", str(head),
         "probe-rehearsal"],
        timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr

    events = [json.loads(l) for l in r.stdout.splitlines()
              if l.startswith("{")]
    names = [e.get("event") for e in events]
    assert "rig healthy" in names, names
    assert "queue done" in names, names
    # queue must have drained successfully into the results file
    qdone = next(e for e in events if e.get("event") == "queue done")
    assert qdone["rc"] == 0
    lines = results.read_text()
    assert '"b16-full-ce"' in lines and '"b16-offloadflash-ce"' in lines

    # the decision fired and flipped to the above-margin challenger
    dec = next(e for e in events if e.get("event") == "headline decision")
    decision = json.loads(dec["out"].splitlines()[-1])
    assert decision["decision"] == "flip", decision
    assert decision["to"] == "b16-offloadflash-ce"
    ov = json.loads(head.read_text())
    assert ov["chosen_from"] == "b16-offloadflash-ce"
    assert ov["probe_tokens_per_s"] > 0
    assert decision["applied"] is True
    # and it must NOT have touched the real repo-root headline override
    # (pick_headline --out redirects the write in rehearsal)
    real_after = (open(real_head).read()
                  if os.path.exists(real_head) else None)
    assert real_after == real_before, \
        "rehearsal wrote the REAL BENCH_HEADLINE.json"


def test_down_path_exits_2_on_deadline(tmp_path):
    """A rig that never recovers must end with exit code 2 (the exit is
    the notification) and never reach the queue."""
    r = _run_watch(
        tmp_path,
        {"DS_CHIP_FORCE_DOWN": "1",
         "DS_RIGWATCH_POLL_S": "1", "DS_RIGWATCH_CONFIRM_S": "0"},
        ["--deadline-hours", "0.001",
         "--results", str(tmp_path / "r.log"), "probe-rehearsal"],
        timeout=120)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "deadline" in r.stdout
    assert "queue start" not in r.stdout
    assert not (tmp_path / "r.log").exists()


def test_rehearse_probe_refuses_without_optin():
    """The rehearsal probe emits gpt2-1.5b-labelled lines; it must be
    impossible to run by accident (e.g. if someone adds it to a default
    queue drain)."""
    env = dict(os.environ)
    env.pop("DS_REHEARSAL", None)
    r = subprocess.run([sys.executable, "tools/rehearse_probe.py"],
                       cwd=ROOT, env=env, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 3
    assert "refused" in r.stdout


def test_pick_headline_ignores_rehearsal_lines_for_real_target(tmp_path):
    """Rehearsal records carry the headline preset label but fake
    numbers; without an explicit --out redirect pick_headline must not
    even consider them."""
    log = tmp_path / "log"
    rec = {"variant": "b16-offloadflash-ce", "preset": "gpt2-1.5b",
           "batch": 16, "remat": "full", "loss_chunk": 2048,
           "fwd_blocks": [1024, 1024], "bwd_blocks": [None, None],
           "tokens_per_s": 99999.0, "mfu": 0.99, "rehearsal": True}
    log.write_text(json.dumps(rec) + "\n")
    r = subprocess.run(
        [sys.executable, "tools/pick_headline.py", str(log)],
        cwd=ROOT, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert json.loads(r.stdout)["decision"] == "no results parsed"
    # with --out (the rehearsal path) the same line IS considered
    r2 = subprocess.run(
        [sys.executable, "tools/pick_headline.py", str(log),
         "--out", str(tmp_path / "h.json")],
        cwd=ROOT, capture_output=True, text=True, timeout=60)
    assert json.loads(r2.stdout)["decision"] != "no results parsed"


def test_rehearsal_item_not_in_default_drain():
    sys.path.insert(0, ROOT)
    from tools.chip_queue import DEFAULT_ITEMS
    assert "probe-rehearsal" not in DEFAULT_ITEMS
    assert "probe" in DEFAULT_ITEMS
