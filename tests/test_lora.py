"""LoRA fine-tuning: frozen base, trained adapters, mergeable result."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.runtime import lora


def _cfg(**kw):
    base = dict(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                max_seq_len=32, dtype=jnp.float32,
                use_flash_attention=False, remat=False)
    base.update(kw)
    return gpt.GPTConfig(**base)


def test_lora_starts_at_base_model(devices):
    """B = 0 makes the adapted forward EXACTLY the base forward."""
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    adapted = lora.add_lora(params, jax.random.PRNGKey(1), rank=4)
    toks = np.random.default_rng(0).integers(0, 128, (2, 9)).astype(np.int32)
    base_out = gpt.forward(params, jnp.asarray(toks), cfg,
                           jax.random.PRNGKey(0), deterministic=True)
    lora_out = gpt.forward(adapted, jnp.asarray(toks), cfg,
                           jax.random.PRNGKey(0), deterministic=True)
    np.testing.assert_array_equal(np.asarray(base_out),
                                  np.asarray(lora_out))


def test_lora_trains_only_adapters(devices):
    """Through the engine with the masked optimizer: loss decreases,
    adapter leaves move, every base leaf stays bit-identical."""
    cfg = _cfg()
    params = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                           jax.random.PRNGKey(1), rank=8)
    n_train, n_total = lora.count_trainable(params)
    # the test model is tiny (embeddings dominate); real models
    # sit well under 1% adapters
    assert 0 < n_train < 0.35 * n_total
    opt = lora.lora_optimizer(
        __import__("optax").adamw(2e-2), params)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1000},
        optimizer=opt)
    before = jax.tree_util.tree_map(np.asarray, engine.state.params)
    toks = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(16)]
    # low-rank adapters on a frozen random base move slowly; the point
    # is a steady decrease with every base leaf bit-frozen (measured
    # trajectory drops ~0.13 over 16 steps)
    assert losses[-1] < losses[0] - 0.1, losses
    after = engine.state.params
    labels = lora.lora_label_tree(before)
    moved = frozen_same = 0
    for (path, b), a, lab in zip(
            jax.tree_util.tree_leaves_with_path(before),
            jax.tree_util.tree_leaves(after),
            jax.tree_util.tree_leaves(labels)):
        if lab == "train":
            moved += int(not np.array_equal(b, np.asarray(a)))
        else:
            assert np.array_equal(b, np.asarray(a)), \
                jax.tree_util.keystr(path)
            frozen_same += 1
    assert moved >= 8          # a and b of several adapted projections
    assert frozen_same > 0


def test_lora_merge_matches_adapted_forward(devices):
    """After training, merge_lora folds the delta: merged == adapted."""
    cfg = _cfg()
    params = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                           jax.random.PRNGKey(1), rank=4)
    import optax
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1000},
        optimizer=lora.lora_optimizer(optax.adamw(3e-3), params))
    toks = np.random.default_rng(1).integers(0, 128, (8, 33)).astype(np.int32)
    for _ in range(4):
        engine.train_batch({"tokens": toks})
    trained = engine.module_state_dict()
    merged = lora.merge_lora(trained)
    assert "lora_a" not in merged["block"]["qkv"]
    x = np.random.default_rng(2).integers(0, 128, (2, 9)).astype(np.int32)
    a = gpt.forward(trained, jnp.asarray(x), cfg, jax.random.PRNGKey(0),
                    deterministic=True)
    m = gpt.forward(merged, jnp.asarray(x), cfg, jax.random.PRNGKey(0),
                    deterministic=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(m),
                               rtol=1e-5, atol=1e-6)


def test_lora_llama_dialect_and_int8_serving(devices):
    """LoRA on the llama dialect (no-bias swiglu entries incl.
    mlp_gate), merged and served int8."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    cfg = gpt.preset("llama-tiny", dtype=jnp.float32,
                     use_flash_attention=False, remat=False)
    params = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                           jax.random.PRNGKey(1), rank=4)
    assert "lora_a" in params["block"]["mlp_gate"]
    merged = lora.merge_lora(params)
    eng = InferenceEngine(config=cfg, params=merged, dtype=jnp.int8)
    toks = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (1, 6)).astype(np.int32)
    out = eng.generate(toks, max_new_tokens=4, temperature=0.0)
    assert ((out >= 0) & (out < cfg.vocab_size)).all()


def test_lora_optimizer_state_is_adapter_sized(devices):
    """The memory story: Adam moments exist only for adapter leaves."""
    import optax
    cfg = _cfg()
    params = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                           jax.random.PRNGKey(1), rank=4)
    opt = lora.lora_optimizer(optax.adamw(1e-3), params)
    state = opt.init(params)
    n_train, n_total = lora.count_trainable(params)
    state_elems = sum(
        x.size for x in jax.tree_util.tree_leaves(state)
        if hasattr(x, "size"))
    # mu + nu for adapters only (plus scalar counts) — far below a
    # full-model Adam state (2 * n_total)
    assert state_elems < 2.2 * n_train + 64, (state_elems, n_train)


def test_lora_composes_with_zero3_and_tp(devices):
    """Adapters ride the default sharding (fsdp on the stacked layer
    dim) alongside ZeRO-3 base params and Megatron TP rules; training
    runs and only adapters move."""
    import optax
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    cfg = _cfg()
    params = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                           jax.random.PRNGKey(1), rank=8)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, model=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "zero_optimization": {"stage": 3},
                "mesh": {"data_parallel_size": 2, "zero_parallel_size": 2,
                         "tensor_parallel_size": 2},
                "steps_per_print": 1000},
        optimizer=lora.lora_optimizer(optax.adamw(1e-2), params),
        mesh=mesh, partition_rules=gpt.gpt_partition_rules())
    before = jax.tree_util.tree_map(np.asarray, engine.state.params)
    toks = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(6)]
    assert losses[-1] < losses[0] - 0.05, losses
    k = engine.state.params["block"]["qkv"]
    assert k["kernel"].sharding.shard_shape(k["kernel"].shape)[-1] \
        == k["kernel"].shape[-1] // 2        # TP column shard intact
    labels = lora.lora_label_tree(before)
    for (path, b), a, lab in zip(
            jax.tree_util.tree_leaves_with_path(before),
            jax.tree_util.tree_leaves(engine.state.params),
            jax.tree_util.tree_leaves(labels)):
        if lab == "freeze":
            assert np.array_equal(b, np.asarray(a)), \
                jax.tree_util.keystr(path)


def test_adapter_save_load_roundtrip(devices, tmp_path):
    """The adapter file carries ONLY lora leaves (tiny); loading onto a
    fresh base reproduces the adapted forward exactly."""
    cfg = _cfg()
    base = gpt.init_params(jax.random.PRNGKey(0), cfg)
    adapted = lora.add_lora(base, jax.random.PRNGKey(1), rank=4)
    # make the adapters non-trivial
    adapted["block"]["qkv"]["lora_b"] = (
        adapted["block"]["qkv"]["lora_b"] + 0.3)
    path = str(tmp_path / "adapter.npz")
    lora.save_adapter(adapted, path)
    import os
    n_train, n_total = lora.count_trainable(adapted)
    assert os.path.getsize(path) < 16 * n_train + 65536   # adapters only

    restored = lora.load_adapter(base, path)
    toks = np.random.default_rng(0).integers(0, 128, (2, 9)).astype(np.int32)
    a = gpt.forward(adapted, jnp.asarray(toks), cfg, jax.random.PRNGKey(0),
                    deterministic=True)
    r = gpt.forward(restored, jnp.asarray(toks), cfg, jax.random.PRNGKey(0),
                    deterministic=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(r))
    # base tree was not mutated
    assert "lora_a" not in base["block"]["qkv"]

    with pytest.raises(KeyError):
        bad = {k: v for k, v in base.items() if k != "block"}
        lora.load_adapter(bad, path)


def test_adapter_load_rejects_mismatched_base(devices, tmp_path):
    """A fan-in/width mismatch (adapter from a different d_model) is a
    loud error at load time, not a jit-time dot_general failure; bf16
    trees save losslessly via fp32."""
    cfg = _cfg()
    adapted = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                            jax.random.PRNGKey(1), rank=4)
    # bf16 adapters save (fp32 widening) and restore
    bf16 = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        adapted)
    path = str(tmp_path / "a.npz")
    lora.save_adapter(bf16, path)
    lora.load_adapter(gpt.init_params(jax.random.PRNGKey(0), cfg), path)

    cfg_small = _cfg(d_model=16, n_heads=2)
    base_small = gpt.init_params(jax.random.PRNGKey(0), cfg_small)
    with pytest.raises(ValueError, match="does not match"):
        lora.load_adapter(base_small, path)


def test_unmerged_adapter_serving_and_int8_base(devices):
    """The inference engine serves an UNMERGED adapted tree (the _dense
    low-rank path runs inside prefill/decode), matching the merged
    model's generation — and composes with an int8-quantized BASE while
    adapters stay float (QLoRA-style serving)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    cfg = _cfg(max_seq_len=64)
    adapted = lora.add_lora(gpt.init_params(jax.random.PRNGKey(0), cfg),
                            jax.random.PRNGKey(1), rank=4)
    adapted["block"]["qkv"]["lora_b"] = (
        adapted["block"]["qkv"]["lora_b"] + 0.25)
    merged = lora.merge_lora(adapted)
    toks = np.random.default_rng(0).integers(0, 128, (2, 8)).astype(np.int32)

    ref = InferenceEngine(config=cfg, params=merged,
                          dtype=jnp.float32).generate(
        toks, max_new_tokens=6, temperature=0.0)
    raw = InferenceEngine(config=cfg, params=adapted,
                          dtype=jnp.float32).generate(
        toks, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(raw, ref)

    q_eng = InferenceEngine(config=cfg, params=adapted, dtype=jnp.int8)
    assert q_eng.params["block"]["qkv"]["q"].dtype == jnp.int8
    assert "lora_a" in q_eng.params["block"]["qkv"]      # adapters float
    out = q_eng.generate(toks, max_new_tokens=6, temperature=0.0)
    assert ((out >= 0) & (out < 128)).all()


def test_config_driven_lora(devices):
    """"lora": {...} in the JSON config adapts the tree and masks the
    optimizer with no user-side code — like every reference feature."""
    cfg = _cfg()
    base = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=base,
        config={"train_batch_size": 8,
                "lora": {"enabled": True, "rank": 8},
                "optimizer": {"type": "adamw", "params": {"lr": 2e-2}},
                "steps_per_print": 1000})
    assert "lora_a" in engine.state.params["block"]["qkv"]
    before = jax.tree_util.tree_map(np.asarray, engine.state.params)
    toks = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(16)]
    assert losses[-1] < losses[0] - 0.1, losses
    labels = lora.lora_label_tree(before)
    for (path, b), a, lab in zip(
            jax.tree_util.tree_leaves_with_path(before),
            jax.tree_util.tree_leaves(engine.state.params),
            jax.tree_util.tree_leaves(labels)):
        if lab == "freeze":
            assert np.array_equal(b, np.asarray(a)), \
                jax.tree_util.keystr(path)

    with pytest.raises(ValueError, match="lora"):
        deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg),
            model_parameters=gpt.init_params(jax.random.PRNGKey(0), cfg),
            config={"train_batch_size": 8,
                    "lora": {"enabled": True},
                    "zero_optimization": {
                        "offload_optimizer": {"device": "cpu"}},
                    "optimizer": {"type": "adamw",
                                  "params": {"lr": 1e-3}}})
