"""FLOPS profiler tests (ref: tests/unit/test_flops_profiler.py —
within_range check of measured flops vs analytic expectation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler, analyze_compiled, analyze_fn, device_peak_flops,
    get_model_profile)
from tests.simple_model import random_batch, simple_model_loss, simple_model_params

TOLERANCE = 0.1


def within_range(val, target, tolerance=TOLERANCE):
    return abs(val - target) / max(target, 1e-9) <= tolerance


def test_matmul_flops_exact():
    m, k, n = 128, 256, 64
    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    p = analyze_fn(lambda x, y: x @ y, a, b, runs=1)
    assert within_range(p["flops"], 2 * m * k * n), p["flops"]
    assert p["macs"] == p["flops"] / 2
    assert p["duration_s"] > 0


def test_gpt_forward_flops_scan_caveat():
    """XLA cost analysis counts a lax.scan body ONCE (trip count is
    opaque to it) — so for the L-layer scan-based GPT the raw count
    lands between the 1-layer and L-layer analytic totals. Models using
    scan-over-layers should supply analytic flops via
    engine.set_flops_per_batch (see _run_flops_profile)."""
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=2, d_model=64,
                        max_seq_len=32, dropout=0.0)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((2, 32), jnp.int32)
    p = analyze_fn(lambda pr, t: gpt.forward(pr, t, cfg), params, toks, runs=1)
    analytic_fwd_all_layers = gpt.train_flops_per_token(cfg, 32) / 3 * 2 * 32
    analytic_one_layer = analytic_fwd_all_layers / cfg.n_layers
    assert analytic_one_layer < p["flops"] < analytic_fwd_all_layers, \
        (analytic_one_layer, p["flops"], analytic_fwd_all_layers)


def test_profiler_class_api(rng):
    params = simple_model_params(hidden_dim=32, nlayers=2)
    prof = FlopsProfiler(simple_model_loss, params)
    prof.start_profile()
    batch = {k: jnp.asarray(v) for k, v in random_batch(8, 32).items()}
    prof.profile(batch, None)
    assert prof.get_total_flops() > 0
    assert "FLOPS" in prof.get_total_flops(as_string=True)
    assert prof.get_total_params() == sum(
        x.size for x in jax.tree_util.tree_leaves(params))
    prof.print_model_profile()  # must not raise
    prof.end_profile()
    assert prof.get_total_flops() == 0.0


def test_profiler_submodules(tmp_path):
    x = jnp.ones((8, 64), jnp.float32)
    w1 = jnp.ones((64, 64), jnp.float32)
    w2 = jnp.ones((64, 16), jnp.float32)
    prof = FlopsProfiler(
        lambda a: (a @ w1) @ w2,
        submodules={
            "fc1": (lambda a: a @ w1, (x,)),
            "fc2": (lambda a: a @ w2, (jnp.ones((8, 64)),)),
        })
    prof.start_profile()
    prof.profile(x)
    assert within_range(prof._sub_profiles["fc1"]["flops"], 2 * 8 * 64 * 64)
    out = tmp_path / "profile.txt"
    prof.print_model_profile(output_file=str(out))
    text = out.read_text()
    assert "fc1" in text and "fc2" in text and "TFLOPS" in text


def test_get_model_profile():
    flops, macs, params = get_model_profile(
        lambda w, x: x @ w, args=(jnp.ones((16, 8)), jnp.ones((4, 16))),
        print_profile=False, as_string=False)
    assert within_range(flops, 2 * 4 * 16 * 8)
    assert params == 16 * 8


def test_analyze_compiled_no_execution():
    calls = []

    def f(x):
        calls.append(1)  # traced once; never re-executed by analysis
        return x * 2 + 1

    jf = jax.jit(f)
    cost = analyze_compiled(jf, jnp.ones((128,)))
    assert cost["flops"] >= 128  # mul + add may fuse; at least one pass
    assert len(calls) == 1


def test_device_peak_flops_lookup():
    # CPU test env: unknown device → None (MFU omitted, no crash)
    assert device_peak_flops() is None or device_peak_flops() > 0


def test_engine_flops_profile_hook(devices, capsys):
    params = simple_model_params(hidden_dim=32, nlayers=2)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        "flops_profiler": {"enabled": True, "profile_step": 2},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    for i in range(3):
        engine.train_batch(random_batch(8, 32, seed=i))
    # profile printed via logger at step 2; just assert the analysis ran
    assert engine._last_step_duration > 0
