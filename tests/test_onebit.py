"""1-bit compression + compressed-optimizer tests
(ref: tests/unit/test_onebit.py, tests/onebit/test_nccl_backend.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.compressed import (_pack_signs, _unpack_signs,
                                               compress, compressed_allreduce,
                                               compression_ratio)
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
from tests.simple_model import random_batch, simple_model_loss, simple_model_params


def test_pack_unpack_roundtrip(devices):
    x = jax.random.normal(jax.random.PRNGKey(0), (1000,))
    packed = _pack_signs(x)
    assert packed.dtype == jnp.uint8
    assert packed.shape[0] == 125
    signs = _unpack_signs(packed, 1000)
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_compress_error_feedback(devices):
    """compressed + error == corrected (lossless accounting)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    e0 = jnp.zeros_like(x)
    packed, scale, err = compress(x, e0)
    from deepspeed_tpu.parallel.compressed import decompress
    comp = decompress(packed, scale, x.size, x.shape)
    np.testing.assert_allclose(np.asarray(comp + err), np.asarray(x),
                               rtol=1e-5, atol=1e-6)
    # scale is the L1 mean
    assert abs(float(scale) - float(jnp.mean(jnp.abs(x)))) < 1e-5


def test_compression_ratio(devices):
    assert compression_ratio((1024, 1024)) > 25  # ~32x for fp32


def test_compressed_allreduce_approximates_mean(devices):
    """Across 8 ranks: compressed allreduce ~ true mean in direction, and
    error feedback accumulates the residual."""
    mesh = make_mesh(MeshSpec(data=8))
    x = jax.random.normal(jax.random.PRNGKey(0), (256,))
    err = jnp.zeros_like(x)
    out, new_err = compressed_allreduce({"g": x}, {"g": err}, mesh)
    # every rank contributed the same value -> result == sign(x)*scale
    _, scale, _ = compress(x, err)
    expect = np.where(np.asarray(x) >= 0, 1.0, -1.0) * float(scale)
    np.testing.assert_allclose(np.asarray(out["g"]), expect, rtol=1e-4)
    # error + compressed == original
    np.testing.assert_allclose(np.asarray(out["g"] + new_err["g"]),
                               np.asarray(x), rtol=1e-4, atol=1e-5)


HIDDEN = 32
BASE = {
    "train_batch_size": 16,
    "steps_per_print": 1000,
}


def _train(opt_cfg, steps=40):
    cfg = dict(BASE)
    cfg["optimizer"] = opt_cfg
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    losses = []
    for i in range(steps):
        m = engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
        losses.append(float(m["loss"]))
    return losses


def test_onebit_adam_converges(devices):
    """1-bit Adam tracks Adam convergence after warmup
    (ref: test_onebit.py convergence pattern)."""
    adam = _train({"type": "adamw", "params": {"lr": 1e-2}})
    onebit = _train({"type": "onebitadam",
                     "params": {"lr": 1e-2, "freeze_step": 10}})
    assert onebit[-1] < onebit[0] * 0.6
    # within 2x of adam's final loss
    assert onebit[-1] < max(adam[-1] * 2.0, 0.1)


def test_zero_one_adam_converges(devices):
    losses = _train({"type": "zerooneadam",
                     "params": {"lr": 1e-2, "var_freeze_step": 20}})
    assert losses[-1] < losses[0] * 0.6


def test_onebit_lamb_converges(devices):
    losses = _train({"type": "onebitlamb",
                     "params": {"lr": 1e-2, "freeze_step": 10}})
    assert losses[-1] < losses[0] * 0.7


def test_variance_frozen_after_freeze_step(devices):
    """nu must stop changing after freeze_step."""
    from deepspeed_tpu.runtime.comm.onebit import onebit_adam
    opt = onebit_adam(1e-2, freeze_step=3)
    params = {"w": jnp.ones((4,))}
    state = opt.init(params)
    g = {"w": jnp.full((4,), 0.5)}
    nus = []
    for i in range(6):
        upd, state = opt.update(g, state, params)
        nus.append(np.asarray(state.nu["w"]).copy())
    assert not np.allclose(nus[1], nus[2])   # still warming up
    np.testing.assert_array_equal(nus[3], nus[4])  # frozen
    np.testing.assert_array_equal(nus[4], nus[5])


# ------------------------------------------------------------------
# engine-level compressed wire path (comm_backend_name="dcn_compressed")
# (ref: runtime/comm/nccl.py:52 compressed_allreduce driving the DP
#  gradient reduction end-to-end)
# ------------------------------------------------------------------

def _train_dp8(extra_cfg, steps=40, return_engine=False):
    # default mesh over the 8 virtual devices = pure data parallelism (dp=8)
    cfg = dict(BASE)
    cfg["train_batch_size"] = 16
    cfg["optimizer"] = {"type": "adamw", "params": {"lr": 1e-2}}
    cfg.update(extra_cfg)
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    losses = []
    for i in range(steps):
        m = engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
        losses.append(float(m["loss"]))
    return (losses, engine) if return_engine else losses


def test_dcn_compressed_convergence_parity(devices):
    """Engine-level compressed grad reduction converges like the plain
    path on the 8-way data mesh."""
    plain = _train_dp8({})
    comp = _train_dp8({"comm_backend_name": "dcn_compressed"})
    assert comp[-1] < comp[0] * 0.5
    assert comp[-1] < max(plain[-1] * 2.0, 0.1)


def test_dcn_compressed_wire_payload_is_packed_uint8(devices):
    """The compiled step's cross-rank collective carries the packed uint8
    sign tensor, not fp32 gradients."""
    _, engine = _train_dp8({"comm_backend_name": "dcn_compressed"},
                           steps=1, return_engine=True)
    batch = engine._shard_batch(random_batch(16, HIDDEN, seed=0))
    hlo = engine._train_step.lower(engine.state, batch).compile().as_text()
    gathers = [ln for ln in hlo.splitlines() if "all-gather" in ln]
    assert any("u8[" in ln for ln in gathers), gathers
    # no full-precision gradient allreduce/all-gather of a [H, H] kernel
    assert not any(f"f32[{HIDDEN},{HIDDEN}]" in ln for ln in gathers)


def test_dcn_compressed_zero2_converges_with_sharded_state(devices):
    """Compressed wire + ZeRO stage 2 — one stage beyond the reference's
    1-bit backends: stage 2's gradient partitioning dissolves (the
    sharded opt update slices the compressed-averaged gradient in the
    auto domain), so error feedback still sees whole per-rank grads
    while the optimizer state keeps its 'data'-axis sharding."""
    losses, engine = _train_dp8(
        {"comm_backend_name": "dcn_compressed",
         # min shard lowered so the tiny test model's 32x32 kernels
         # actually shard over dp=8 (default 1024 leaves them whole)
         "zero_optimization": {"stage": 2, "stage3_min_shard_size": 1}},
        return_engine=True)
    assert losses[-1] < losses[0] * 0.5
    # the stage-2 memory win survives compression: moments are sharded
    moments = [x for x in jax.tree_util.tree_leaves(engine.state.opt_state)
               if getattr(x, "ndim", 0) == 2]
    assert moments, "no matrix-shaped optimizer-state leaves found"
    assert any(m.sharding.shard_shape(m.shape) != tuple(m.shape)
               for m in moments), \
        "stage-2 optimizer state not sharded under dcn_compressed"


def test_dcn_compressed_rejects_zero3_single_replica(devices):
    """ZeRO-3 with one replica has no cross-replica axis to compress —
    1-bit noise over the exact fsdp arithmetic would be pure loss, so
    the engine demands replica_parallel_size > 1."""
    cfg = dict(BASE)
    cfg["optimizer"] = {"type": "adamw", "params": {"lr": 1e-2}}
    cfg["comm_backend_name"] = "dcn_compressed"
    cfg["zero_optimization"] = {"stage": 3}
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    with pytest.raises(ValueError, match="replica_parallel_size"):
        deepspeed_tpu.initialize(model=simple_model_loss,
                                 model_parameters=params, config=cfg)


# ------------------------------------------------------------------
# compressed x fsdp composition (PERF.md "Compressed DCN x ZeRO-fsdp"):
# exact gradient reduction over fsdp/ICI in the auto domain, 1-bit
# error-feedback wire over the outer 'data'/DCN axis — one full ZeRO
# stage beyond both the reference (stage <= 1) and round 4 (stage <= 2)
# ------------------------------------------------------------------

def _train_meshed(mesh, stage, steps=8):
    cfg = dict(BASE)
    cfg["optimizer"] = {"type": "adamw", "params": {"lr": 1e-2}}
    cfg["comm_backend_name"] = "dcn_compressed"
    cfg["zero_optimization"] = {"stage": stage, "stage3_min_shard_size": 1}
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg,
        mesh=mesh)
    losses = []
    for i in range(steps):
        m = engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
        losses.append(float(m["loss"]))
    return losses, engine


def test_dcn_compressed_zero3_fsdp_matches_pure_dp_oracle(devices):
    """(data=2, fsdp=4, stage 3) must reproduce the (data=2) pure-DP
    compressed trajectory EXACTLY (mod reduction order): the fsdp axis
    is exact arithmetic (auto-domain reduce-scatter + param gathers),
    so only the 2-way compressed 'data' wire touches the math — the
    same wire the pure-DP oracle runs."""
    oracle_mesh = make_mesh(MeshSpec(data=2), devices=jax.devices()[:2])
    comp_mesh = make_mesh(MeshSpec(data=2, fsdp=4))
    oracle, _ = _train_meshed(oracle_mesh, stage=2)
    comp, engine = _train_meshed(comp_mesh, stage=3)
    np.testing.assert_allclose(comp, oracle, rtol=1e-5)
    assert comp[-1] < comp[0] * 0.5  # and it genuinely learns

    # the wire stays packed uint8 AND shard-sized: each device gathers
    # its 1/fsdp sign shard over 'data' — compression and sharding
    # multiply (per-rank DCN bytes P/(8*fsdp))
    batch = engine._shard_batch(random_batch(16, HIDDEN, seed=0))
    hlo = engine._train_step.lower(engine.state, batch).compile().as_text()
    gathers = [ln for ln in hlo.splitlines() if "all-gather" in ln]
    assert any("u8[" in ln for ln in gathers), gathers

    # per-device error residual covers exactly its (data, fsdp) shard —
    # nothing replicated
    err = [e for e in jax.tree_util.tree_leaves(engine.state.comm_error)
           if getattr(e, "ndim", 0) == 3]
    assert err, "no matrix error residuals found"
    e = err[0]
    shard = e.sharding.shard_shape(e.shape)
    assert shard[0] == e.shape[0] // 2          # data axis split
    assert shard[1:] != e.shape[1:]             # fsdp split of param dims

    # ZeRO-3 memory layout survives compression: params sharded over fsdp
    kernels = [p for p in jax.tree_util.tree_leaves(engine.state.params)
               if getattr(p, "ndim", 0) == 2]
    assert any(k.sharding.shard_shape(k.shape) != tuple(k.shape)
               for k in kernels), "stage-3 params not sharded under " \
                                  "dcn_compressed x fsdp"
