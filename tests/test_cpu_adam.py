"""Native host Adam/Adagrad parity tests.

Mirrors the reference's CPU-Adam checks (ref: tests/unit/test_cpu_adam.py —
kernel vs torch.optim reference within fp tolerance); the golden here is a
pure-numpy Adam.
"""

import numpy as np
import pytest

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad


def numpy_adamw(params, grads, m, v, t, lr, b1, b2, eps, wd):
    m = b1 * m + (1 - b1) * grads
    v = b2 * v + (1 - b2) * grads * grads
    mhat = m / (1 - b1 ** t)
    vhat = v / (1 - b2 ** t)
    params = params * (1 - lr * wd) - lr * mhat / (np.sqrt(vhat) + eps)
    return params, m, v


@pytest.mark.parametrize("n", [17, 4096, 100_003])
def test_adamw_matches_numpy(n):
    rng = np.random.default_rng(0)
    p = rng.standard_normal(n).astype(np.float32)
    p_ref = p.copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-2, betas=(0.9, 0.999), eps=1e-8,
                           weight_decay=0.01, adamw_mode=True)
    for t in range(1, 4):
        g = rng.standard_normal(n).astype(np.float32)
        opt.step("p", p, g, lr=1e-2)
        p_ref, m, v = numpy_adamw(p_ref, g, m, v, t, 1e-2, 0.9, 0.999,
                                  1e-8, 0.01)
    np.testing.assert_allclose(p, p_ref, rtol=2e-5, atol=2e-6)
    st = opt.state_arrays("p")
    np.testing.assert_allclose(st["exp_avg"], m, rtol=2e-5, atol=2e-6)


def test_adam_l2_mode():
    # adamw_mode=False folds weight decay into the gradient (classic Adam+L2)
    rng = np.random.default_rng(1)
    n = 1000
    p = rng.standard_normal(n).astype(np.float32)
    p_ref = p.copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.1, adamw_mode=False)
    g = rng.standard_normal(n).astype(np.float32)
    opt.step("p", p, g)
    g_ref = g + 0.1 * p_ref
    m = 0.1 * g_ref
    v = 0.001 * g_ref * g_ref
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    p_ref = p_ref - 1e-3 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(p, p_ref, rtol=2e-5, atol=2e-6)


def test_bf16_copyback():
    rng = np.random.default_rng(2)
    n = 5000
    p = rng.standard_normal(n).astype(np.float32)
    out = np.empty(n, np.uint16)
    opt = DeepSpeedCPUAdam(lr=1e-2)
    opt.step("p", p, rng.standard_normal(n).astype(np.float32),
             params_bf16_out=out)
    # bf16 round-trip of the updated fp32 master
    import jax.numpy as jnp
    bf = out.view(jnp.bfloat16.dtype).astype(np.float32)
    np.testing.assert_allclose(bf, p, rtol=1e-2, atol=1e-2)


def test_adagrad():
    rng = np.random.default_rng(3)
    n = 777
    p = rng.standard_normal(n).astype(np.float32)
    p_ref = p.copy()
    acc = np.zeros(n, np.float32)
    opt = DeepSpeedCPUAdagrad(lr=1e-2, eps=1e-10)
    for _ in range(3):
        g = rng.standard_normal(n).astype(np.float32)
        opt.step("p", p, g)
        acc += g * g
        p_ref -= 1e-2 * g / (np.sqrt(acc) + 1e-10)
    np.testing.assert_allclose(p, p_ref, rtol=2e-5, atol=2e-6)
