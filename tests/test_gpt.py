"""GPT model tests: shapes, loss sanity, TP/fsdp sharding, engine training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt


def tiny_cfg(**kw):
    d = dict(vocab_size=256, n_layers=2, n_heads=4, d_model=64,
             max_seq_len=64, use_flash_attention=False, remat=False,
             dtype=jnp.float32)
    d.update(kw)
    return gpt.GPTConfig(**d)


def test_forward_shapes(devices):
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = gpt.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_loss_at_init_near_uniform(devices):
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    loss = gpt.loss_fn(params, {"tokens": tokens}, jax.random.PRNGKey(2), cfg)
    # at init the LM should be close to uniform: loss ~= ln(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0


def test_num_params_matches(devices):
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    actual = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))
    assert actual == gpt.num_params(cfg)


def test_causality(devices):
    """Changing a future token must not affect past logits."""
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    l1 = gpt.forward(params, t1, cfg)
    l2 = gpt.forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_engine_trains_gpt(devices):
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds_cfg = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_min_shard_size": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds_cfg,
        partition_rules=gpt.gpt_partition_rules())
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(15)]
    assert losses[-1] < losses[0] - 0.5, losses


def test_tp_gpt_matches_dp(devices):
    """TP=2 logits must match single-device logits."""
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    ref = gpt.forward(params, tokens, cfg)

    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    from deepspeed_tpu.parallel.sharding import param_specs, to_named
    mesh = make_mesh(MeshSpec(data=-1, model=2))
    specs = to_named(param_specs(params, mesh, zero_stage=0,
                                 rules=gpt.gpt_partition_rules()), mesh)
    params_tp = jax.device_put(params, specs)
    out = jax.jit(lambda p, t: gpt.forward(p, t, cfg))(params_tp, tokens)  # dslint: disable=DS002 — one-shot parity check, jitted once per test
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-4)


def test_gqa_model_trains_and_matches_reference_shapes(devices):
    """n_kv_heads < n_heads: fused qkv carries H + 2*Hkv heads, the model
    trains, and the loss is finite."""
    import deepspeed_tpu
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        n_kv_heads=2)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    assert params["block"]["qkv"]["kernel"].shape == (2, 32, (4 + 4) * 8)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000})
    toks = {"tokens": np.random.default_rng(0).integers(
        0, 128, (8, 17)).astype(np.int32)}
    losses = [float(eng.train_batch(toks)["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_offload_flash_remat_matches_full(devices, pallas_interpret):
    """remat_policy='offload_flash' (flash residuals stream to pinned
    host — the cpu_checkpointing analog, ref activation_checkpointing/
    checkpointing.py:28) must produce identical grads to full remat;
    only memory placement differs. Uses the real flash kernel (interpret
    mode) so the "flash_out"/"flash_lse" tags actually exist and the
    offload policy engages."""
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt import remat_policy
    from deepspeed_tpu.ops.attention import flash as F

    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q, k, v = (jax.random.normal(kk, (1, 256, 4, 64), jnp.float32)
               for kk in ks[:3])
    w = jax.random.normal(ks[3], (256, 256), jnp.float32) * 0.05

    def block(q, w):
        o = F.flash_attention(q, k, v, causal=True, block_q=128,
                              block_kv=128)
        h = o.reshape(1, 256, 256) @ w
        return (h ** 2).sum()

    def loss(pol):
        f = jax.checkpoint(block, policy=remat_policy(pol, flash=True))
        return jax.jit(jax.grad(f, argnums=(0, 1)))(q, w)

    gf = loss("full")
    go = loss("offload_flash")
    for a, b in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
