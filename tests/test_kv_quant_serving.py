"""int8 KV-cache × serving-feature integration tests (tentpole:
DS_KV_QUANT plumbing through inference/engine.py slot programs +
inference/serving.py dispatch + inference/paged_cache.py scale pools).

The contract under test (docs/KV_QUANT.md): kv_quant="off" is BIT-
IDENTICAL to a ServingEngine that never heard of the knob; int8 keeps
greedy streams argmax-stable on the smoke configs (>= 99% token match
vs the unquantized static engine) while composing with every serving
feature — shared-prefix COW, speculative rollback across block edges,
eviction/requeue, chaos faults — at the SAME compiled-program count and
zero steady-state recompiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.telemetry import Telemetry
from deepspeed_tpu.utils.faults import Fault, injected


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


@pytest.fixture(scope="module")
def eng(devices):
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def serve(eng, prompts, n_new=8, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 24)
    kw.setdefault("prefill_chunk", 8)
    srv = ServingEngine(eng, **kw)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=n_new)
                   for i, p in enumerate(prompts)])
    return srv, out


def _match_rate(out, refs):
    tot = match = 0
    for i, ref in enumerate(refs):
        got = np.asarray(out[i])
        ref = np.asarray(ref)
        n = min(len(got), len(ref))
        match += int((got[:n] == ref[:n]).sum())
        tot += max(len(got), len(ref))
    return match / max(tot, 1)


# ---------------------------------------------------------------------------
# off mode is bit-identical to today's serving
# ---------------------------------------------------------------------------

def test_kv_quant_off_is_bit_identical(eng):
    prompts = prompts_of((5, 9, 12, 3))
    _, base = serve(eng, prompts)                     # knob never passed
    _, off = serve(eng, prompts, kv_quant="off")
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], base[i])


def test_kv_quant_env_resolution(eng, monkeypatch):
    monkeypatch.setenv("DS_KV_QUANT", "int8")
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=8)
    assert srv.kv_quant == "int8" and srv.cache.quantized
    # explicit off beats the env var
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=8,
                        kv_quant="off")
    assert srv.kv_quant == "off" and srv.cache.k_scale is None


# ---------------------------------------------------------------------------
# int8 greedy parity vs the unquantized static engine
# ---------------------------------------------------------------------------

def test_kv_quant_int8_greedy_match(eng):
    """>= 99% greedy token match vs the unquantized static-engine
    streams on the CPU smoke config (docs/KV_QUANT.md tolerance)."""
    prompts = prompts_of((5, 9, 12, 3))
    refs = [eng.generate(p[None], max_new_tokens=8)[0] for p in prompts]
    srv, out = serve(eng, prompts, kv_quant="int8")
    assert srv.stats["completed"] == len(prompts)
    assert srv.stats["peak_occupancy"] > 1            # really batched
    assert _match_rate(out, refs) >= 0.99


def test_kv_quant_int8_rotary_gqa_window(devices):
    """int8 composes with rotary positions, grouped KV heads and
    sliding-window masking — the full feature stack the fp path
    serves."""
    import dataclasses
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, rotary_dim=4, use_wpe=False,
                              n_kv_heads=2, attn_window=6)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    e = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((7, 11), seed=2)
    refs = [e.generate(p[None], max_new_tokens=6)[0] for p in prompts]
    _, out = serve(e, prompts, n_new=6, kv_quant="int8")
    assert _match_rate(out, refs) >= 0.99


# ---------------------------------------------------------------------------
# x shared-prefix cache: sharing + COW on the int8 layout
# ---------------------------------------------------------------------------

def test_kv_quant_warm_prefix_matches_cold(eng):
    """Warm (prefix hits) int8 serving == cold int8 serving token-for-
    token: shared full blocks are reused with their scales, and the
    read-modify-requantize write path never touches a published
    block."""
    sys_prompt = np.arange(1, 25, dtype=np.int32)
    r = np.random.default_rng(0)
    prompts = [np.concatenate([sys_prompt,
                               r.integers(1, 128, 6).astype(np.int32)])
               for _ in range(4)]
    cold_srv, cold = serve(eng, prompts, block_size=8, prefill_chunk=16,
                           prefix_cache=False, kv_quant="int8")
    warm_srv, warm = serve(eng, prompts, block_size=8, prefill_chunk=16,
                           prefix_cache=True, kv_quant="int8")
    assert warm_srv.stats["prefix_hits"] > 0
    assert warm_srv.stats["prefill_chunks"] < cold_srv.stats[
        "prefill_chunks"]
    for i in range(len(prompts)):
        np.testing.assert_array_equal(warm[i], cold[i])


def test_kv_quant_cow_divergence_mid_block(eng):
    """Mid-block divergence under int8: the COW copy carries BOTH the
    int8 block bytes and the per-block scales, so the diverging request
    still matches its cold int8 stream exactly."""
    base = np.arange(1, 31, dtype=np.int32)
    div = base.copy()
    div[21] = 99                                      # inside block 2
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                        prefill_chunk=16, prefix_cache=True,
                        kv_quant="int8")
    out1 = srv.run([ServeRequest(rid="a", prompt=base, max_new_tokens=8)])
    out2 = srv.run([ServeRequest(rid="b", prompt=div, max_new_tokens=8)])
    assert srv.cache.cow_copies == 1
    assert srv.stats["prefix_hits"] == 1
    for p, got in ((base, out1["a"]), (div, out2["b"])):
        cold = ServingEngine(eng, num_slots=2, block_size=8,
                             num_blocks=24, prefill_chunk=16,
                             prefix_cache=False, kv_quant="int8")
        ref = cold.run([ServeRequest(rid="x", prompt=p,
                                     max_new_tokens=8)])["x"]
        np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# x speculative decoding: rollback across block edges with scales
# ---------------------------------------------------------------------------

def test_kv_quant_spec_rollback_block_boundary(eng):
    """Speculative int8 serving with a draft chunk size that forces
    rejects to straddle block edges: rollback trims the tail block and
    the next owner's write live-masks the stale int8 lanes, so the
    spec-on int8 stream equals the spec-off int8 stream's match rate
    against itself — here they must be token-identical since acceptance
    is target-argmax equality ON THE SAME quantized cache state only
    when histories coincide; we assert completion + near-total match."""
    prompts = prompts_of((5, 9, 12), seed=1)
    s_srv, s_out = serve(eng, prompts, n_new=10, spec_decode=True,
                         kv_quant="int8")
    p_srv, p_out = serve(eng, prompts, n_new=10, spec_decode=False,
                         kv_quant="int8")
    assert s_srv.stats["completed"] == 3
    assert s_srv.stats["spec_accepted"] > 0           # really speculated
    assert _match_rate(s_out, [p_out[i] for i in range(3)]) >= 0.99


def test_kv_quant_spec_eviction_requeue(eng):
    """Tiny pool + speculation + int8: decode growth exhausts the free
    list mid-stream, the evicted request requeues and completes; the
    rollback/requeue bookkeeping never corrupts the scale pools
    (completion + finite pools is the assert)."""
    prompts = prompts_of((12, 12, 12), seed=3)
    srv, out = serve(eng, prompts, n_new=12, num_blocks=10,
                     spec_decode=True, kv_quant="int8")
    assert srv.stats["completed"] == 3
    assert srv.stats["evictions"] >= 1
    assert np.isfinite(np.asarray(srv.cache.k_scale)).all()
    assert np.isfinite(np.asarray(srv.cache.v_scale)).all()


# ---------------------------------------------------------------------------
# compile contract: same program count, fp twins stay cold
# ---------------------------------------------------------------------------

def test_kv_quant_compile_count_contract(devices):
    """DS_KV_QUANT=int8 keeps the serving compile contract: exactly one
    prefill + one decode executable (the _q jit twins), the fp programs
    stay COLD (quant never compiles both sets), and a second identical
    workload compiles NOTHING."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    e = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)

    def run_workload():
        srv = ServingEngine(e, num_slots=2, block_size=4, num_blocks=7,
                            prefill_chunk=8, spec_decode=False,
                            kv_quant="int8")
        srv.cache.watermark = 0
        out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                       ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return srv, out

    srv, warm_out = run_workload()
    assert srv.stats["evictions"] >= 1
    n_prefill = cache_size(e._prefill_slot_q)
    if n_prefill is not None:
        assert n_prefill == 1
        assert cache_size(e._decode_slots_q) == 1
        # the unquantized programs never compiled: same program COUNT,
        # not 2x — quant swaps the set, it doesn't add one
        assert cache_size(e._prefill_slot) == 0
        assert cache_size(e._decode_slots) == 0

    watch = CompileWatch(max_compiles=0, label="int8 serving steady state")
    watch.wrap(e._prefill_slot_q)
    watch.wrap(e._decode_slots_q)
    with watch:                            # raises RecompileError on exit
        srv2, out = run_workload()
    assert srv2.stats["evictions"] >= 1
    for rid in ("a", "b"):
        np.testing.assert_array_equal(out[rid], warm_out[rid])


# ---------------------------------------------------------------------------
# chaos: cache.quantize fault degrades the step, never the pool
# ---------------------------------------------------------------------------

def test_kv_quant_chaos_transient_fault_retries_clean(eng):
    """A transient device error at the cache.quantize site (fires
    BEFORE dispatch, donated pools untouched) is retried by the serving
    backoff and the final streams are identical to a fault-free int8
    run — the retry replays against uncorrupted int8 pools + scales."""
    prompts = prompts_of((5, 9, 12), seed=1)
    _, clean = serve(eng, prompts, n_new=6, kv_quant="int8",
                     retry_backoff_s=0.0)
    with injected(Fault("cache.quantize", "device_error", step=1),
                  seed=0) as inj:
        srv, out = serve(eng, prompts, n_new=6, kv_quant="int8",
                         retry_backoff_s=0.0)
    assert ("cache.quantize", "device_error", 1) in inj.fired
    assert srv.stats["retries"] >= 1
    for i in range(3):
        np.testing.assert_array_equal(out[i], clean[i])
    assert np.isfinite(np.asarray(srv.cache.k_scale)).all()


# ---------------------------------------------------------------------------
# telemetry: capacity gauges + sampled quant-error histogram
# ---------------------------------------------------------------------------

def test_kv_quant_telemetry_gauges_and_error_histogram(eng):
    prompts = prompts_of((5, 9), seed=1)
    srv, _ = serve(eng, prompts, kv_quant="int8", telemetry=Telemetry())
    reg = srv.metrics
    bpt = reg.gauge("kv_cache_bytes_per_token").value
    assert bpt == pytest.approx(
        srv.cache.bytes_per_token
        + srv.cache.scale_bytes_per_block / srv.cache.block_size)
    assert reg.gauge("kv_pool_dtype").value == 8      # int8 = 8 bits
    h = reg.histogram("serving_kv_quant_error")
    assert h.count > 0                                # sampled at least once
    # the observed upper bound is half a quantization step: tiny
    assert h.sum / h.count < 1.0
    text = reg.to_prometheus()
    assert "kv_cache_bytes_per_token" in text
    assert "serving_kv_quant_error" in text
    # off mode: gauges report the fp layout, no error histogram samples
    srv0, _ = serve(eng, prompts, kv_quant="off", telemetry=Telemetry())
    assert srv0.metrics.gauge("kv_cache_bytes_per_token").value == \
        srv0.cache.bytes_per_token
    assert srv0.metrics.histogram("serving_kv_quant_error").count == 0


def test_kv_quant_telemetry_off_noop(eng):
    """Default-off telemetry stays a no-op under quant — no registry,
    no sampled device pulls beyond the step sync."""
    prompts = prompts_of((5,), seed=1)
    srv, out = serve(eng, prompts, kv_quant="int8")
    assert srv._h_kv_err is None
    assert len(out[0]) == 5 + 8           # prompt + generated stream
