"""Flash-attention kernel parity tests vs pure-jnp reference
(ref: tests/unit/test_cuda_forward.py / test_cuda_backward.py — kernel
parity within tolerances). Runs in pallas interpret mode on CPU; the same
code compiles for TPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import flash as F


def _rand_qkv(B=2, S=256, H=4, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    q = jax.random.normal(ks[0], shape, dtype)
    k = jax.random.normal(ks[1], shape, dtype)
    v = jax.random.normal(ks[2], shape, dtype)
    return q, k, v


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret):
    """Force pallas interpret mode on CPU (shared conftest fixture)."""
    yield


@pytest.mark.parametrize("causal", [True, False])
def test_forward_parity(devices, causal):
    q, k, v = _rand_qkv()
    out = F.flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
    ref = F.mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_forward_multi_block(devices):
    q, k, v = _rand_qkv(S=512)
    out = F.flash_attention(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = F.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_head_dim_padding(devices):
    """D=64 < 128 lanes must be padded transparently."""
    q, k, v = _rand_qkv(D=64)
    out = F.flash_attention(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = F.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_parity(devices, causal):
    q, k, v = _rand_qkv(B=1, S=256, H=2, D=64)

    def f_flash(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=causal,
                                         block_q=128, block_kv=128) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(F.mha_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_bf16_forward(devices):
    q, k, v = _rand_qkv(dtype=jnp.bfloat16)
    out = F.flash_attention(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = F.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_mask_forward_parity(devices, causal):
    q, k, v = _rand_qkv(B=2, S=256, H=2, D=32)
    rng = np.random.default_rng(0)
    kv_mask = jnp.asarray((rng.random((2, 256)) > 0.25).astype(np.float32))
    out = F.flash_attention(q, k, v, causal=causal, block_q=128,
                            block_kv=128, kv_mask=kv_mask)
    ref = F.mha_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_kv_mask_grads_parity(devices):
    q, k, v = _rand_qkv(B=1, S=256, H=2, D=32, seed=3)
    rng = np.random.default_rng(1)
    kv_mask = jnp.asarray((rng.random((1, 256)) > 0.3).astype(np.float32))
    # loss masks padded QUERY rows (standard contract)
    row_w = kv_mask[..., None, None]

    def loss_flash(q, k, v):
        o = F.flash_attention(q, k, v, causal=False, block_q=128,
                              block_kv=128, kv_mask=kv_mask)
        return ((o * row_w) ** 2).sum()

    def loss_ref(q, k, v):
        o = F.mha_reference(q, k, v, causal=False, kv_mask=kv_mask)
        return ((o * row_w) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_encoder_layer_masked_flash_path(devices, monkeypatch):
    """The encoder attention core with a padding mask matches its jnp
    path when routed through the (interpret-mode) flash kernel — and the
    flash path must actually be TAKEN (the core's try/except fallback
    would otherwise make this comparison vacuous)."""
    from deepspeed_tpu.ops.attention import flash as flash_mod
    from deepspeed_tpu.ops.transformer.encoder_layer import (
        DeepSpeedTransformerConfig, _attention_core)
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=2,
                                     attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0,
                                     num_hidden_layers=1)
    B, S, H, D = 2, 128, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in ks)
    mask = jnp.asarray(
        (np.random.default_rng(0).random((B, S)) > 0.2).astype(np.float32))

    calls = []
    orig = flash_mod.flash_attention

    def recording(*a, **kw):
        calls.append(kw.get("kv_mask") is not None)
        return orig(*a, **kw)

    monkeypatch.setattr(flash_mod, "flash_attention", recording)
    with_flash = _attention_core(q, k, v, mask, cfg, None, True,
                                 allow_flash=True)
    assert calls == [True], "masked flash path was not taken"
    no_flash = _attention_core(q, k, v, mask, cfg, None, True,
                               allow_flash=False)
    valid = np.asarray(mask)[:, :, None, None] > 0
    np.testing.assert_allclose(np.asarray(with_flash) * valid,
                               np.asarray(no_flash) * valid,
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_forward_parity(devices, causal):
    """Grouped-query attention: 4 q heads sharing 2 kv heads == the
    repeated-kv dense reference."""
    q, _, _ = _rand_qkv(B=2, S=256, H=4, D=32)
    ks = jax.random.split(jax.random.PRNGKey(7), 2)
    k = jax.random.normal(ks[0], (2, 256, 2, 32), jnp.float32)
    v = jax.random.normal(ks[1], (2, 256, 2, 32), jnp.float32)
    out = F.flash_attention(q, k, v, causal=causal, block_q=128,
                            block_kv=128)
    ref = F.mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_grads_parity(devices, causal):
    q, _, _ = _rand_qkv(B=1, S=256, H=4, D=32, seed=8)
    ks = jax.random.split(jax.random.PRNGKey(9), 2)
    k = jax.random.normal(ks[0], (1, 256, 2, 32), jnp.float32)
    v = jax.random.normal(ks[1], (1, 256, 2, 32), jnp.float32)

    def loss_f(q, k, v):
        return (F.flash_attention(q, k, v, causal=causal, block_q=128,
                                  block_kv=128) ** 2).sum()

    def loss_r(q, k, v):
        return (F.mha_reference(q, k, v, causal=causal) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gf, gr, "qkv"):
        assert a.shape == b.shape, n
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=n)


@pytest.mark.parametrize("window", [32, 100, 256])
def test_sliding_window_forward_parity(devices, window):
    q, k, v = _rand_qkv(B=1, S=512, H=2, D=32)
    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, window=window)
    ref = F.mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_sliding_window_grads_parity(devices):
    q, k, v = _rand_qkv(B=1, S=512, H=2, D=32, seed=11)
    W = 96

    def loss_f(q, k, v):
        return (F.flash_attention(q, k, v, causal=True, block_q=128,
                                  block_kv=128, window=W) ** 2).sum()

    def loss_r(q, k, v):
        return (F.mha_reference(q, k, v, causal=True, window=W) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=n)


def test_sliding_window_model_matches_reference(devices):
    """GPT with attn_window on the jnp path == windowed dense reference."""
    from deepspeed_tpu.models import gpt as gpt_lib
    cfg = gpt_lib.GPTConfig(vocab_size=64, n_layers=1, n_heads=2,
                            d_model=16, max_seq_len=64, dtype=jnp.float32,
                            use_flash_attention=False, remat=False,
                            attn_window=8)
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 2, 8), jnp.float32)
    out = gpt_lib._attention(q, q, q, cfg)
    ref = F.mha_reference(q, q, q, causal=True, window=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window", [32, 100, 256])
def test_sliding_window_masked_impl_forward_parity(devices, window):
    """The "masked" fallback (in-body mask over plain causal geometry —
    the Mosaic-proven construct set; see _norm_window) must match both
    the dense reference and the banded implementation exactly: the two
    impls differ only in which blocks are fetched/skipped, never in
    what any in-band block computes."""
    q, k, v = _rand_qkv(B=1, S=512, H=2, D=32)
    masked = F.flash_attention(q, k, v, causal=True, block_q=128,
                               block_kv=128, window=window,
                               window_impl="masked")
    ref = F.mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(masked), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    banded = F.flash_attention(q, k, v, causal=True, block_q=128,
                               block_kv=128, window=window,
                               window_impl="banded")
    np.testing.assert_allclose(np.asarray(masked), np.asarray(banded),
                               rtol=1e-6, atol=1e-6)


def test_sliding_window_masked_impl_grads_parity(devices):
    q, k, v = _rand_qkv(B=1, S=512, H=2, D=32, seed=11)
    W = 96

    def loss_m(q, k, v):
        return (F.flash_attention(q, k, v, causal=True, block_q=128,
                                  block_kv=128, window=W,
                                  window_impl="masked") ** 2).sum()

    def loss_r(q, k, v):
        return (F.mha_reference(q, k, v, causal=True, window=W) ** 2).sum()

    gm = jax.grad(loss_m, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gm, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=n)


def test_window_impl_env_default(devices, monkeypatch):
    """DS_FLASH_WINDOW_IMPL=masked flips the default, so hardware
    deployments can quarantine the banded kernel without code changes
    (PARITY.md note)."""
    q, k, v = _rand_qkv(B=1, S=256, H=2, D=32)
    monkeypatch.setenv("DS_FLASH_WINDOW_IMPL", "masked")
    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, window=64)
    ref = F.mha_reference(q, k, v, causal=True, window=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    monkeypatch.setenv("DS_FLASH_WINDOW_IMPL", "bogus")
    with pytest.raises(ValueError, match="window impl"):
        F.flash_attention(q, k, v, causal=True, block_q=128,
                          block_kv=128, window=64)


def test_window_gqa_segments_compose(devices):
    """window + GQA + segment_ids in one call — all masks and the
    grouped kv maps compose."""
    q, _, _ = _rand_qkv(B=1, S=256, H=4, D=32, seed=13)
    ks = jax.random.split(jax.random.PRNGKey(14), 2)
    k = jax.random.normal(ks[0], (1, 256, 2, 32), jnp.float32)
    v = jax.random.normal(ks[1], (1, 256, 2, 32), jnp.float32)
    segs = jnp.asarray(np.repeat([0, 1], 128)[None], jnp.int32)
    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, window=64, segment_ids=segs)
    ref = F.mha_reference(q, k, v, causal=True, window=64,
                          segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_bwd_block_override_parity(devices):
    """Separate backward tiles (bwd_block_q/kv != fwd blocks) must not
    change gradients — only the dq/dkv kernel tiling."""
    q, k, v = _rand_qkv(S=512)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    base = functools.partial(F.flash_attention, causal=True,
                             block_q=256, block_kv=256)
    tuned = functools.partial(F.flash_attention, causal=True,
                              block_q=256, block_kv=256,
                              bwd_block_q=128, bwd_block_kv=128)
    g0 = jax.grad(loss(base), argnums=(0, 1, 2))(q, k, v)
    g1 = jax.grad(loss(tuned), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
