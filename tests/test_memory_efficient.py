"""Memory-efficient bf16 training state (bf16 masters + bf16 moments with
stochastic-rounding updates).

Capability test in the spirit of the reference's BF16 optimizer coverage
(ref: tests/unit/test_fp16.py optimizer matrix + runtime/bf16_optimizer.py)
— the memory-efficient mode halves training-state bytes vs fp32 masters
and must still converge.
"""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.ops.adam import stochastic_round_bf16


def tiny_cfg(**kw):
    d = dict(vocab_size=64, n_layers=2, n_heads=2, d_model=32,
             max_seq_len=32, dtype=jnp.bfloat16, remat=False,
             use_flash_attention=False)
    d.update(kw)
    return gpt.GPTConfig(**d)


def make_engine(params, cfg, mem_eff):
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={
            "train_batch_size": 8,
            "bf16": {"enabled": True, "memory_efficient": mem_eff},
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        })
    return eng


def test_stochastic_rounding_unbiased():
    """E[SR(x)] == x for x between two bf16 grid points."""
    lo = jnp.asarray(1.0, jnp.bfloat16)
    hi = jnp.asarray(1.0078125, jnp.bfloat16)  # next bf16 after 1.0
    x = jnp.full((20000,), 1.0 + 0.25 * 0.0078125, jnp.float32)
    r = stochastic_round_bf16(x, jax.random.PRNGKey(0))
    vals = np.asarray(r, np.float32)
    assert set(np.unique(vals)) <= {float(lo), float(hi)}
    frac_hi = (vals == float(hi)).mean()
    assert 0.2 < frac_hi < 0.3, frac_hi  # expect ~0.25
    # negative values round toward larger magnitude the same way
    rn = stochastic_round_bf16(-x, jax.random.PRNGKey(1))
    np.testing.assert_allclose(np.asarray(rn, np.float32).mean(),
                               -float(np.asarray(x[0])), rtol=1e-3)


def test_state_dtypes_are_bf16(rng):
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = make_engine(params, cfg, mem_eff=True)
    # master weights bf16
    p_leaves = jax.tree_util.tree_leaves(eng.state.params)
    assert all(l.dtype == jnp.bfloat16 for l in p_leaves)
    # moments bf16
    from deepspeed_tpu.ops.adam import ScaleByAdamState
    mus = [s for s in jax.tree_util.tree_leaves(eng.state.opt_state)
           if hasattr(s, "dtype") and s.ndim > 0]
    assert all(l.dtype == jnp.bfloat16 for l in mus)
    # state bytes: 8 per param (p + m + v + grad transient excluded)
    data = {"tokens": rng.integers(0, cfg.vocab_size, (8, 17))
            .astype(np.int32)}
    m = eng.train_batch(data)
    assert np.isfinite(float(m["loss"]))


def test_memory_efficient_converges_like_fp32(rng):
    """Loss trajectory tracks the fp32-master engine within tolerance
    (stochastic rounding keeps sub-ulp updates in expectation)."""
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    e32 = make_engine(params, cfg, mem_eff=False)
    e16 = make_engine(params, cfg, mem_eff=True)
    data = {"tokens": rng.integers(0, cfg.vocab_size, (8, 17))
            .astype(np.int32)}
    l32, l16 = [], []
    for _ in range(20):
        l32.append(float(e32.train_batch(data)["loss"]))
        l16.append(float(e16.train_batch(data)["loss"]))
    # both learn, and final losses are in the same regime
    assert l32[-1] < l32[0] and l16[-1] < l16[0]
    assert abs(l16[-1] - l32[-1]) < 0.25 * max(1.0, l32[0] - l32[-1]) + 0.2


def test_memory_efficient_requires_bf16():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    try:
        deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 8,
                    "bf16": {"enabled": False, "memory_efficient": True},
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
        assert False, "expected ValueError"
    except ValueError as e:
        assert "memory_efficient" in str(e)
