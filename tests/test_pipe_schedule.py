"""Schedule/partitioning unit tests — no devices
(ref: tests/unit/test_pipe_schedule.py:157 pattern: validate instruction
streams directly)."""

import pytest

from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec,
                                               partition_balanced,
                                               partition_uniform)
from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                 InferenceSchedule,
                                                 LoadMicroBatch, OptimizerStep,
                                                 RecvActivation, RecvGrad,
                                                 ReduceGrads, ReduceTiedGrads,
                                                 SendActivation, SendGrad,
                                                 TrainSchedule)


def _flat(sched):
    cmds = []
    for step in sched.steps():
        cmds.extend(step)
    return cmds


def test_train_schedule_counts():
    """Every stage does M forwards and M backwards + epilogue."""
    for stage in range(4):
        sched = TrainSchedule(micro_batches=8, stages=4, stage_id=stage)
        cmds = _flat(sched)
        assert sum(isinstance(c, ForwardPass) for c in cmds) == 8
        assert sum(isinstance(c, BackwardPass) for c in cmds) == 8
        assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1
        assert sum(isinstance(c, ReduceGrads) for c in cmds) == 1


def test_train_schedule_first_last_stage_io():
    first = _flat(TrainSchedule(micro_batches=4, stages=2, stage_id=0))
    assert any(isinstance(c, LoadMicroBatch) for c in first)
    assert not any(isinstance(c, RecvActivation) for c in first)
    assert any(isinstance(c, SendActivation) for c in first)
    assert any(isinstance(c, RecvGrad) for c in first)
    assert not any(isinstance(c, SendGrad) for c in first)

    last = _flat(TrainSchedule(micro_batches=4, stages=2, stage_id=1))
    assert any(isinstance(c, RecvActivation) for c in last)
    assert not any(isinstance(c, SendActivation) for c in last)
    assert any(isinstance(c, SendGrad) for c in last)
    assert not any(isinstance(c, RecvGrad) for c in last)


def test_train_schedule_1f1b_order():
    """First stage: P-1 warmup forwards before the first backward."""
    sched = TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    kinds = [type(c).__name__ for c in _flat(sched)
             if type(c).__name__ in ("ForwardPass", "BackwardPass")]
    first_bwd = kinds.index("BackwardPass")
    assert kinds[:first_bwd].count("ForwardPass") == 3 + 1  # warmup + 1 steady fwd
    # last stage alternates F,B from the start
    sched_last = TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    kinds_last = [type(c).__name__ for c in _flat(sched_last)
                  if type(c).__name__ in ("ForwardPass", "BackwardPass")]
    assert kinds_last[:4] == ["ForwardPass", "BackwardPass"] * 2


def test_train_schedule_buffer_bound():
    """1F1B memory: num buffers shrinks for later stages."""
    assert TrainSchedule(8, 4, 0).num_pipe_buffers() == 4
    assert TrainSchedule(8, 4, 2).num_pipe_buffers() == 2
    assert TrainSchedule(8, 4, 3).num_pipe_buffers() == 2


def test_inference_schedule():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    cmds = _flat(sched)
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
    assert not any(isinstance(c, BackwardPass) for c in cmds)
    steps = list(sched.steps())
    assert len(steps) == 4 + 2 - 1


def test_instruction_repr_eq():
    assert ForwardPass(3) == ForwardPass(3)
    assert ForwardPass(3) != ForwardPass(4)
    assert "buffer_id=3" in repr(ForwardPass(3))


# ---- partitioning ---------------------------------------------------------

def test_partition_uniform():
    assert partition_uniform(10, 2) == [0, 5, 10]
    assert partition_uniform(10, 3) == [0, 4, 7, 10]
    assert partition_uniform(2, 4) == [0, 1, 2, 2, 2]


def test_partition_balanced():
    parts = partition_balanced([10, 1, 1, 1, 1, 10], 2)
    # balanced split puts the two heavy layers in different parts
    assert parts[0] == 0 and parts[-1] == 6
    w = [10, 1, 1, 1, 1, 10]
    left = sum(w[parts[0]:parts[1]])
    right = sum(w[parts[1]:parts[2]])
    assert max(left, right) <= 14


def test_pipeline_module_partition_methods():
    layers = [LayerSpec("Embed", None, lambda: 100)] + \
        [LayerSpec("Block", None, lambda: 10) for _ in range(6)] + \
        [LayerSpec("Head", None, lambda: 100)]
    pm_u = PipelineModule(layers, num_stages=2, partition_method="uniform")
    assert pm_u.parts == [0, 4, 8]
    pm_p = PipelineModule(layers, num_stages=2, partition_method="parameters")
    assert pm_p.parts[0] == 0 and pm_p.parts[-1] == 8
    pm_t = PipelineModule(layers, num_stages=2, partition_method="type:Block")
    counts = [sum(1 for i in pm_t.layers_of_stage(s)
                  if layers[i].typename == "Block") for s in range(2)]
    assert counts == [3, 3]


def test_tied_layers():
    layers = [TiedLayerSpec("Embed", None, lambda: 10, key="embed")] + \
        [LayerSpec("Block", None, lambda: 10) for _ in range(4)] + \
        [TiedLayerSpec("Head", None, lambda: 10, key="embed")]
    pm = PipelineModule(layers, num_stages=2, partition_method="uniform")
    assert pm.tied_groups["embed"] == [0, 5]
    assert pm.tied_stages("embed") == [0, 1]
