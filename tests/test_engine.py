"""Engine end-to-end tests: convergence parity across ZeRO stages and
precisions (ref: tests/unit/test_zero.py, test_fp16.py — tiny-model
convergence under each config)."""

import numpy as np
import pytest

import deepspeed_tpu
from tests.simple_model import random_batch, simple_model_loss, simple_model_params

HIDDEN = 32


def _train(config, steps=40, seed=0):
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=seed)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=config)
    losses = []
    for i in range(steps):
        # cycle a small fixed dataset so loss decreases monotonically-ish
        batch = random_batch(config["train_batch_size"], HIDDEN, seed=i % 4)
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    return engine, losses


BASE = {
    "train_batch_size": 16,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
    "steps_per_print": 1000,
}


def test_fp32_dp_converges(devices):
    _, losses = _train(dict(BASE))
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stages_converge(devices, stage):
    cfg = dict(BASE)
    cfg["zero_optimization"] = {"stage": stage, "stage3_min_shard_size": 1}
    cfg["bf16"] = {"enabled": True}
    _, losses = _train(cfg)
    assert losses[-1] < losses[0] * 0.6, (stage, losses)


def test_zero_matches_ddp(devices):
    """Stage-3 sharded training must match replicated training closely
    (ref: test_zero.py convergence-vs-torch pattern)."""
    _, base_losses = _train(dict(BASE), steps=10)
    cfg = dict(BASE)
    cfg["zero_optimization"] = {"stage": 3, "stage3_min_shard_size": 1}
    _, z3_losses = _train(cfg, steps=10)
    np.testing.assert_allclose(base_losses, z3_losses, rtol=2e-3, atol=2e-4)


def test_grad_accumulation_equivalence(devices):
    """gas=2 with the same global batch must track gas=1 closely."""
    cfg1 = dict(BASE)
    cfg1["gradient_accumulation_steps"] = 1
    _, l1 = _train(cfg1, steps=5)
    cfg2 = dict(BASE)
    cfg2["gradient_accumulation_steps"] = 2
    _, l2 = _train(cfg2, steps=5)
    np.testing.assert_allclose(l1, l2, rtol=1e-3, atol=1e-4)


def test_fp16_dynamic_loss_scale(devices):
    cfg = dict(BASE)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    engine, losses = _train(cfg, steps=20)
    assert losses[-1] < losses[0]
    assert engine.get_loss_scale() >= 1.0


def test_fp16_overflow_skips_step(devices):
    """A batch engineered to overflow fp16 must skip the step and halve the
    scale (ref: test_fp16.py overflow handling)."""
    cfg = dict(BASE)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 15, "hysteresis": 1}
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    scale0 = engine.get_loss_scale()
    bad = random_batch(16, HIDDEN)
    bad["x"] = bad["x"] * 1e30  # force inf in fwd/bwd
    m = engine.train_batch(bad)
    assert bool(m["overflow"])
    assert engine.get_loss_scale() < scale0
    assert engine.skipped_steps == 1


def test_gradient_clipping(devices):
    cfg = dict(BASE)
    cfg["gradient_clipping"] = 1e-4
    _, losses = _train(cfg, steps=3)  # runs without error; tiny clip ~ frozen
    assert abs(losses[0] - losses[-1]) < 0.5


def test_lamb_optimizer(devices):
    cfg = dict(BASE)
    cfg["optimizer"] = {"type": "lamb", "params": {"lr": 1e-2}}
    _, losses = _train(cfg)
    assert losses[-1] < losses[0] * 0.7


def test_scheduler_integration(devices):
    cfg = dict(BASE)
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_max_lr": 1e-2, "warmup_num_steps": 5}}
    engine, losses = _train(cfg, steps=8)
    assert losses[-1] < losses[0]


def test_tp_engine(devices):
    """Tensor-parallel mesh with megatron rules on the MLP fixture."""
    from deepspeed_tpu.parallel.sharding import PartitionRule
    from jax.sharding import PartitionSpec as P
    cfg = dict(BASE)
    cfg["mesh"] = {"tensor_parallel_size": 2}
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2)
    rules = [PartitionRule(r"layer_0/kernel", P(None, "model")),
             PartitionRule(r"layer_1/kernel", P("model", None))]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg,
        partition_rules=rules)
    losses = []
    for i in range(10):
        m = engine.train_batch(random_batch(16, HIDDEN, seed=i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]

def test_prefetch_loader(devices):
    """PrefetchLoader yields pre-sharded batches one step ahead; training
    through it matches the expected number of steps with device-committed
    arrays."""
    from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                                  PrefetchLoader)
    from tests.simple_model import simple_model_loss, simple_model_params
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=0)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000})
    r = np.random.default_rng(0)
    data = [{"x": r.standard_normal(HIDDEN).astype(np.float32),
             "y": np.zeros((), np.float32)} for _ in range(24)]
    loader = DeepSpeedDataLoader(data, batch_size=8, shuffle=False)
    seen = 0
    for batch in PrefetchLoader(loader, engine, depth=2):
        import jax
        assert all(isinstance(v, jax.Array) for v in batch.values())
        engine.train_batch(batch)
        seen += 1
    assert seen == 3
