"""Block-sparse attention parity tests vs dense masked reference
(ref: tests/unit/test_sparse_attention.py — compares Triton kernels
against a dense torch implementation)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, VariableSparsityConfig, SparseSelfAttention,
    SparseAttentionUtils, blocksparse_attention, blocksparse_attention_jnp,
    blocksparse_attention_kernel, blocksparse_reference, make_lut,
    sparse_density)

B, S, H, D = 2, 256, 4, 32
BLOCK = 32


def _qkv(seed=0, s=S, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, s, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret):
    yield


# ---------------------------------------------------------------- layouts

def test_dense_layout_all_ones():
    cfg = DenseSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(S)
    assert layout.shape == (H, S // BLOCK, S // BLOCK)
    assert layout.all()


def test_fixed_layout_unidirectional_is_lower_triangular():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(S)
    assert np.triu(layout[0], 1).sum() == 0
    # diagonal always active
    assert np.diagonal(layout[0]).all()


def test_fixed_layout_global_patterns_differ_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=BLOCK, num_local_blocks=4,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(S)
    assert not np.array_equal(layout[0], layout[1])


def test_bigbird_layout_has_window_global_random():
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    layout = cfg.make_layout(S)
    nb = S // BLOCK
    assert layout[0, 0, :].all() and layout[0, :, 0].all()  # global
    for r in range(nb):
        assert layout[0, r, r] == 1  # window includes diagonal
    assert 0 < sparse_density(layout) < 1


def test_longformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0, 2])
    layout = cfg.make_layout(S)
    assert layout[0, 2, :].all() and layout[0, :, 2].all()


def test_variable_layout_rejects_bad_global_ranges():
    with pytest.raises(ValueError):
        VariableSparsityConfig(num_heads=H, global_block_indices=[3],
                               global_block_end_indices=[2])


def test_make_lut_roundtrip():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2)
    layout = cfg.make_layout(S)
    lut, valid = make_lut(layout)
    nb = S // BLOCK
    assert lut.shape[0] == H and lut.shape[1] == nb
    # every active block appears exactly once per row
    for h in range(H):
        for r in range(nb):
            cols = sorted(lut[h, r][valid[h, r]].tolist())
            assert cols == sorted(np.nonzero(layout[h, r])[0].tolist())


# ---------------------------------------------------------- parity: jnp path

@pytest.mark.parametrize("cfg_fn", [
    lambda: FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                                attention="bidirectional"),
    lambda: FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                                attention="unidirectional"),
    lambda: BigBirdSparsityConfig(num_heads=H, block=BLOCK),
    lambda: BSLongformerSparsityConfig(num_heads=H, block=BLOCK),
    lambda: DenseSparsityConfig(num_heads=H, block=BLOCK),
])
def test_jnp_parity_vs_dense(devices, cfg_fn):
    cfg = cfg_fn()
    layout = cfg.make_layout(S)
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    q, k, v = _qkv()
    out = blocksparse_attention(q, k, v, layout, causal=causal,
                                use_kernel=False)
    ref = blocksparse_reference(q, k, v, layout, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_jnp_parity_with_masks(devices):
    cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(S)
    q, k, v = _qkv()
    kp = np.zeros((B, S), np.float32)
    kp[:, S - 17:] = -1e9  # pad out the tail
    am = np.ones((S, S), np.float32)
    am[:, :3] = 0
    out = blocksparse_attention(q, k, v, layout, key_padding_mask=kp,
                                key_padding_mask_mode="add", attn_mask=am,
                                attn_mask_mode="mul", use_kernel=False)
    ref = blocksparse_reference(q, k, v, layout, key_padding_mask=kp,
                                key_padding_mask_mode="add", attn_mask=am,
                                attn_mask_mode="mul")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_jnp_grads_match_dense(devices):
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(S)
    q, k, v = _qkv()

    def loss_sparse(q, k, v):
        o = blocksparse_attention(q, k, v, layout, causal=True,
                                  use_kernel=False)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = blocksparse_reference(q, k, v, layout, causal=True)
        return jnp.sum(o * o)

    gs = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


# ------------------------------------------------------- parity: pallas path

@pytest.mark.parametrize("causal", [False, True])
def test_kernel_parity(devices, causal):
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention=("unidirectional" if causal
                                         else "bidirectional"))
    layout = cfg.make_layout(S)
    lut, valid = make_lut(layout)
    q, k, v = _qkv()
    out = blocksparse_attention_kernel(q, k, v, lut, valid, BLOCK,
                                       causal=causal)
    ref = blocksparse_reference(q, k, v, layout, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_grads(devices):
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(128)
    lut, valid = make_lut(layout)
    q, k, v = _qkv(s=128)

    def loss(q, k, v):
        o = blocksparse_attention_kernel(q, k, v, lut, valid, BLOCK)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = blocksparse_reference(q, k, v, layout)
        return jnp.sum(o * o)

    gs = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------------- module

def test_sparse_self_attention_module(devices):
    attn = SparseSelfAttention(
        FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                            attention="unidirectional"))
    q, k, v = _qkv()
    out = attn(q, k, v)
    assert out.shape == q.shape
    # layout cache hit
    assert S in attn._cache
    out2 = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2))


def test_pad_to_block_size():
    ids = jnp.ones((2, 100), jnp.int32)
    mask = jnp.ones((2, 100), jnp.float32)
    pad_len, ids_p, mask_p, _, _, _ = SparseAttentionUtils.pad_to_block_size(
        block=32, input_ids=ids, attention_mask=mask, pad_token_id=7)
    assert pad_len == 28 and ids_p.shape == (2, 128)
    assert int(ids_p[0, -1]) == 7 and float(mask_p[0, -1]) == 0.0
    out = SparseAttentionUtils.unpad_sequence_output(pad_len,
                                                     jnp.ones((2, 128, 8)))
    assert out.shape == (2, 100, 8)


def test_build_sparsity_config_from_engine_config():
    from deepspeed_tpu.runtime.config import SparseAttentionConfig
    from deepspeed_tpu.ops.sparse_attention import build_sparsity_config
    for mode, cls in [("dense", DenseSparsityConfig),
                      ("fixed", FixedSparsityConfig),
                      ("variable", VariableSparsityConfig),
                      ("bigbird", BigBirdSparsityConfig),
                      ("bslongformer", BSLongformerSparsityConfig)]:
        sa = SparseAttentionConfig.from_dict({"mode": mode, "block": BLOCK})
        cfg = build_sparsity_config(sa, num_heads=H)
        assert isinstance(cfg, cls)
        assert cfg.make_layout(S).shape == (H, S // BLOCK, S // BLOCK)


def test_rpe_parity(devices):
    cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(S)
    q, k, v = _qkv()
    rpe = np.random.default_rng(1).normal(size=(S, S)).astype(np.float32)
    am = np.ones((S, S), np.float32)
    am[:, 5:9] = 0  # mul mask must still mask when rpe is present
    out = blocksparse_attention(q, k, v, layout, attn_mask=am,
                                attn_mask_mode="mul", rpe=rpe,
                                use_kernel=False)
    ref = blocksparse_reference(q, k, v, layout, attn_mask=am,
                                attn_mask_mode="mul", rpe=rpe)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_fully_masked_row_outputs_zero(devices):
    # layout whose row 0 only attends to a block entirely above the causal
    # diagonal: the kernel must emit zeros like the jnp path
    nb = 4
    layout = np.zeros((1, nb, nb), np.int64)
    layout[0, 0, 2] = 1            # above diagonal for causal rows in block 0
    layout[0, 1:, 0] = 1
    np.fill_diagonal(layout[0][1:, 1:], 1)
    lut, valid = make_lut(layout)
    s = nb * BLOCK
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (1, s, 1, D)) for kk in ks)
    out_k = blocksparse_attention_kernel(q, k, v, lut, valid, BLOCK,
                                         causal=True)
    out_j = blocksparse_attention_jnp(q, k, v, lut, valid, BLOCK, causal=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_j),
                               rtol=2e-5, atol=2e-5)
    assert np.abs(np.asarray(out_k)[0, :BLOCK]).max() == 0.0


def test_max_seq_length_enforced(devices):
    attn = SparseSelfAttention(
        FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2),
        max_seq_length=128)
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="max_seq_length"):
        attn(q, k, v)
