"""ZeRO-Infinity parameter streaming tests.

Mirrors the reference's param-swap coverage
(ref: tests/unit/test_zero.py ZeRO-3 convergence + the NVMe swap configs
in tests/unit/test_aio.py / swap_tensor tests): parity of the streamed
layered engine against the fused in-HBM engine, grad-accumulation
equivalence, factory-form construction, and checkpoint round trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.runtime.zero.param_offload import InfinityParamEngine


def tiny_cfg(**kw):
    d = dict(vocab_size=64, n_layers=3, n_heads=2, d_model=32,
             max_seq_len=32, dtype=jnp.bfloat16, remat=False,
             use_flash_attention=False)
    d.update(kw)
    return gpt.GPTConfig(**d)


def ds_config(**kw):
    d = {
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-2, "weight_decay": 0.0}},
        "steps_per_print": 10_000,
    }
    d.update(kw)
    return d


def batch_of(rng, cfg, batch=8, seq=16):
    return {"tokens": rng.integers(0, cfg.vocab_size,
                                   (batch, seq + 1)).astype(np.int32)}


def test_streamed_parity_with_fused_engine(rng):
    """Streamed per-layer execution must match the fused in-HBM engine's
    loss trajectory (same init, same data, same optimizer family)."""
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    eng_fused, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_config())
    eng_stream, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config())
    assert isinstance(eng_stream, InfinityParamEngine)

    data = batch_of(rng, cfg)
    fused_losses, stream_losses = [], []
    for _ in range(4):
        fused_losses.append(float(eng_fused.train_batch(data)["loss"]))
        stream_losses.append(float(eng_stream.train_batch(data)["loss"]))
    # identical math up to bf16 grad accumulation differences
    np.testing.assert_allclose(fused_losses, stream_losses, rtol=7e-2)
    # both must actually learn
    assert stream_losses[-1] < stream_losses[0]
    assert eng_stream.device_memory_bytes() < sum(
        np.prod(s) for flat in eng_stream.shapes for s in flat) * 2 + \
        sum(np.prod(s) for s in eng_stream.other_shapes) * 2 + 1


def test_gradient_accumulation(rng):
    """gas=2 over the split batch == one batch of the same samples."""
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(1), cfg)
    data = batch_of(rng, cfg, batch=8)

    e1, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config())
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config(gradient_accumulation_steps=2))

    m1 = e1.train_batch(data)
    m2 = e2.train_batch(data)
    np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=2e-2)
    np.testing.assert_allclose(m1["grad_norm"], m2["grad_norm"], rtol=2e-2)
    # params after the step agree
    p1 = e1.gathered_params()
    p2 = e2.gathered_params()
    a = np.asarray(p1["block"]["qkv"]["kernel"], np.float32)
    b = np.asarray(p2["block"]["qkv"]["kernel"], np.float32)
    np.testing.assert_allclose(a, b, atol=2e-2)


def test_factory_form_never_materializes_stack(rng):
    """Factory construction (for > host-RAM-stack models) trains and its
    layer slices match the equivalent direct construction."""
    cfg = tiny_cfg(n_layers=2)
    fac = gpt.host_param_factory(7, cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=fac,
        config=ds_config())
    assert eng.L == 2
    data = batch_of(rng, cfg)
    losses = [float(eng.train_batch(data)["loss"]) for _ in range(3)]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_grad_clipping_exact_global_norm(rng):
    """Clip uses the exact global norm across ALL layers+other (two-phase
    norm-then-step, ref stage_1_and_2.py:1670-1754)."""
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(2), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config(gradient_clipping=1e-4))
    data = batch_of(rng, cfg)
    m = eng.train_batch(data)
    assert m["grad_norm"] > 1e-4  # reported norm is pre-clip
    # a second step still behaves (params moved only a tiny amount)
    m2 = eng.train_batch(data)
    assert np.isfinite(m2["loss"])


def test_checkpoint_roundtrip(rng):
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(3), cfg)
    e1, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config())
    data = batch_of(rng, cfg)
    e1.train_batch(data)
    sd = e1.state_dict()

    e2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config())
    e2.load_state_dict(sd)
    l1 = float(e1.train_batch(data)["loss"])
    l2 = float(e2.train_batch(data)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-3)


def test_nvme_moment_tier(rng, tmp_path):
    """Adam moments on NVMe through the pipelined swapper
    (ref: pipelined_optimizer_swapper.py:60) — trains and converges."""
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(4), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config(zero_optimization={
            "stage": 3,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path)},
        }))
    data = batch_of(rng, cfg)
    losses = [float(eng.train_batch(data)["loss"]) for _ in range(3)]
    assert losses[-1] < losses[0]


def test_nvme_checkpoint_roundtrip(rng, tmp_path):
    """NVMe-tier moments survive a state_dict round trip (they are pulled
    off NVMe into the checkpoint and pushed back on load)."""
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(5), cfg)

    def build(swap_dir):
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.layered_model(cfg), model_parameters=params,
            config=ds_config(zero_optimization={
                "stage": 3,
                "offload_optimizer": {"device": "nvme",
                                      "nvme_path": str(swap_dir)}}))
        return eng

    e1 = build(tmp_path / "s1")
    data = batch_of(rng, cfg)
    e1.train_batch(data)
    sd = e1.state_dict()
    # the checkpoint carries the group moments, not just 'other'
    assert any(k.startswith("G") for k in sd["adam"]), list(sd["adam"])

    e2 = build(tmp_path / "s2")
    e2.load_state_dict(sd)
    l1 = float(e1.train_batch(data)["loss"])
    l2 = float(e2.train_batch(data)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-3)


def test_cross_tier_restore_keeps_moments(rng, tmp_path):
    """NVMe-format checkpoints restore into a host-tier engine (and back)
    without silently resetting the Adam moments."""
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(6), cfg)

    e_nvme, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config(zero_optimization={
            "stage": 3,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "s1")}}))
    data = batch_of(rng, cfg)
    e_nvme.train_batch(data)
    sd = e_nvme.state_dict()

    e_cpu, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config())
    e_cpu.load_state_dict(sd)
    # moments actually landed in the host adam under per-leaf keys
    assert any(k.startswith("G0.") for k in e_cpu.adam.state), \
        list(e_cpu.adam.state)
    l1 = float(e_nvme.train_batch(data)["loss"])
    l2 = float(e_cpu.train_batch(data)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-3)

    # and host-tier state into an NVMe engine
    sd2 = e_cpu.state_dict()
    e_nvme2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=ds_config(zero_optimization={
            "stage": 3,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "s2")}}))
    e_nvme2.load_state_dict(sd2)
    l3 = float(e_nvme2.train_batch(data)["loss"])
    np.testing.assert_allclose(l3, float(e_cpu.train_batch(data)["loss"]),
                               rtol=1e-3)


def fp16_ds_config(**kw):
    d = {
        "train_batch_size": 8,
        "fp16": {"enabled": True, "initial_scale_power": 8,
                 "loss_scale_window": 4, "hysteresis": 1,
                 "min_loss_scale": 1.0},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-2, "weight_decay": 0.0}},
        "steps_per_print": 10_000,
    }
    d.update(kw)
    return d


def test_fp16_streamed_parity_with_fused_engine(rng):
    """fp16 loss-scaled mode in the Infinity tier (the capability row the
    reference's fp16 partition swapper covers,
    ref partitioned_param_swapper.py:37): loss parity with the fused
    fp16 engine and actual learning."""
    cfg = tiny_cfg(dtype=jnp.float16)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    eng_fused, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=fp16_ds_config())
    eng_stream, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=fp16_ds_config())
    assert isinstance(eng_stream, InfinityParamEngine)
    assert eng_stream.fp16 and eng_stream.cur_scale == 2.0 ** 8

    data = batch_of(rng, cfg)
    fused, stream = [], []
    for _ in range(4):
        fused.append(float(eng_fused.train_batch(data)["loss"]))
        m = eng_stream.train_batch(data)
        assert not m["overflow"]
        stream.append(float(m["loss"]))
    np.testing.assert_allclose(fused, stream, rtol=7e-2)
    assert stream[-1] < stream[0]


def test_fp16_overflow_skips_and_backs_off(rng):
    """An overflowing step must leave params untouched, report
    overflow=True and halve the dynamic scale (hysteresis=1)."""
    cfg = tiny_cfg(dtype=jnp.float16)
    params = gpt.init_params(jax.random.PRNGKey(1), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=fp16_ds_config())
    data = batch_of(rng, cfg)

    before_master = [m.copy() for m in eng.master[0]]
    scale0 = eng.cur_scale
    eng.cur_scale = 1e30          # seed overflows in fp16 immediately
    m = eng.train_batch(data)
    assert m["overflow"]
    assert eng.skipped_steps == 1
    assert eng.cur_scale == 1e30 / 2.0          # backed off
    for a, b in zip(before_master, eng.master[0]):
        np.testing.assert_array_equal(a, b)     # step skipped

    # recovery: scale back to sane, training proceeds
    eng.cur_scale = scale0
    m = eng.train_batch(data)
    assert not m["overflow"] and np.isfinite(m["loss"])


def test_fp16_scale_growth_after_window(rng):
    cfg = tiny_cfg(dtype=jnp.float16)
    params = gpt.init_params(jax.random.PRNGKey(2), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=fp16_ds_config())
    data = batch_of(rng, cfg)
    s0 = eng.cur_scale
    for _ in range(4):            # loss_scale_window = 4 good steps
        assert not eng.train_batch(data)["overflow"]
    assert eng.cur_scale == s0 * 2


def test_fp16_checkpoint_restores_scaler(rng):
    cfg = tiny_cfg(dtype=jnp.float16)
    params = gpt.init_params(jax.random.PRNGKey(3), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=fp16_ds_config())
    data = batch_of(rng, cfg)
    eng.train_batch(data)
    eng.cur_scale = 123.0
    sd = eng.state_dict()

    eng2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.layered_model(cfg), model_parameters=params,
        config=fp16_ds_config())
    eng2.load_state_dict(sd)
    assert eng2.cur_scale == 123.0
    assert eng2.step_count == eng.step_count
    m = eng2.train_batch(data)
    assert not m["overflow"] and np.isfinite(m["loss"])
