"""Pipelined-execution tests on the 8-device CPU mesh: parity with
non-pipelined forward, convergence, and composition with the engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def tiny_cfg(n_layers=4, **kw):
    d = dict(vocab_size=128, n_layers=n_layers, n_heads=4, d_model=32,
             max_seq_len=32, use_flash_attention=False, remat=False,
             dtype=jnp.float32)
    d.update(kw)
    return gpt.GPTConfig(**d)


def test_pipeline_loss_matches_dense(devices):
    """Pipelined loss over 4 stages == plain loss (same params/batch)."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 17)).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}

    ref = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0), cfg,
                            deterministic=True))

    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4, num_micro=2)
    with jax.set_mesh(mesh):
        pl_loss = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(ref, pl_loss, rtol=1e-5)


def test_pipeline_grads_match_dense(devices):
    """Pipeline autodiff (incl. tied embedding psum) == dense grads."""
    cfg = tiny_cfg(n_layers=2)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (4, 17)).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}

    g_ref = jax.grad(lambda p: gpt.loss_fn(p, dict(batch),
                                           jax.random.PRNGKey(0), cfg,
                                           deterministic=True))(params)
    mesh = make_mesh(MeshSpec(pipe=2, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2, num_micro=2)
    with jax.set_mesh(mesh):
        g_pl = jax.jit(jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(0))))(params)

    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_pl = dict(jax.tree_util.tree_leaves_with_path(g_pl))
    for path, leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_pl[path]),
            rtol=2e-4, atol=2e-5,
            err_msg=jax.tree_util.keystr(path))


def test_pipeline_engine_trains(devices):
    """Full engine integration: pp=4 x dp=2, ZeRO-1, loss decreases."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4, num_micro=4)
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules())
    data = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(12)]
    assert losses[-1] < losses[0] - 0.5, losses
    # block params must actually be sharded over pipe
    qkv = engine.state.params["block"]["qkv"]["kernel"]
    assert qkv.sharding.shard_shape(qkv.shape)[0] == cfg.n_layers // 4


# ------------------------------------------------------------------
# memory-bounded 1F1B schedule (ref: pipe/schedule.py:189 TrainSchedule)
# ------------------------------------------------------------------

def test_1f1b_loss_and_grads_match_dense(devices):
    """The 1F1B program (manual fwd+bwd scan) reproduces dense loss and
    gradients, including the tied-embedding path."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 17)).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}

    ref_l = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0),
                              cfg, deterministic=True))
    g_ref = jax.grad(lambda p: gpt.loss_fn(p, dict(batch),
                                           jax.random.PRNGKey(0), cfg,
                                           deterministic=True))(params)

    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                        num_micro=4, schedule="1f1b")
    with jax.set_mesh(mesh):
        l = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
        g = jax.jit(jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(0))))(params)
    np.testing.assert_allclose(ref_l, l, rtol=1e-5)
    flat_pl = dict(jax.tree_util.tree_leaves_with_path(g))
    for path, leaf in jax.tree_util.tree_leaves_with_path(g_ref):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_pl[path]),
            rtol=2e-4, atol=2e-5, err_msg=jax.tree_util.keystr(path))


def test_1f1b_activation_memory_bounded(devices):
    """Compiled peak temp memory: the per-microbatch marginal cost of the
    1F1B program stays far below fill-drain GPipe's (whose live window is
    O(M) vs O(stages))."""
    def temp_bytes(schedule, M):
        cfg = tiny_cfg(n_layers=4, d_model=64, remat=True)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tokens = np.random.default_rng(0).integers(
            0, 128, (M * 2, 17)).astype(np.int32)
        batch = {"tokens": jnp.asarray(tokens)}
        mesh = make_mesh(MeshSpec(pipe=4, data=-1))
        loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                            num_micro=M, schedule=schedule)
        with jax.set_mesh(mesh):
            comp = jax.jit(jax.grad(
                lambda p: loss_fn(p, batch, jax.random.PRNGKey(0)))
            ).lower(params).compile()
        return comp.memory_analysis().temp_size_in_bytes

    marginal_gpipe = temp_bytes("gpipe", 16) - temp_bytes("gpipe", 4)
    marginal_1f1b = temp_bytes("1f1b", 16) - temp_bytes("1f1b", 4)
    # 1f1b's growth is only the batch-proportional input/dx buffers;
    # gpipe additionally stacks every microbatch's live activations
    assert marginal_1f1b < 0.4 * marginal_gpipe, (
        marginal_1f1b, marginal_gpipe)


def test_1f1b_engine_trains(devices):
    """Engine integration with the 1F1B schedule: pp=4 x dp=2."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                        num_micro=4, schedule="1f1b")
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules())
    data = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(12)]
    assert losses[-1] < losses[0] - 0.5, losses


def test_3d_parallel_engine(devices):
    """3D composition pipe=2 x model=2 x data=2 through the engine
    (ref: PipeModelDataParallelTopology, runtime/pipe/topology.py:246) —
    parity vs the dense loss and convergence."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=2, data=2, model=2))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2, num_micro=2)
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules(tp=True))

    # parity of the first loss vs dense single-device compute
    data = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(data)},
                            jax.random.PRNGKey(0), cfg, deterministic=True))
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(10)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[-1] < losses[0] - 0.4, losses

    # all three axes genuinely active: stage dim over pipe, qkv out-dim
    # over model
    qkv = engine.state.params["block"]["qkv"]["kernel"]
    shard = qkv.sharding.shard_shape(qkv.shape)
    assert shard[0] == cfg.n_layers // 2       # pipe
    assert shard[2] == qkv.shape[2] // 2       # model (TP)


def test_pipeline_with_fsdp(devices):
    """Pipeline (stacked stage params over 'pipe') composed with ZeRO-3
    fsdp sharding of the within-stage dims — the composition the round-1
    verdict flagged as unproven. pipe=2 x fsdp=2 x data=2."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(1), cfg)
    mesh = make_mesh(MeshSpec(pipe=2, data=2, fsdp=2))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2, num_micro=2)
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_min_shard_size": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules())

    data = np.random.default_rng(1).integers(0, 128, (8, 33)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(data)},
                            jax.random.PRNGKey(0), cfg, deterministic=True))
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(10)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[-1] < losses[0] - 0.4, losses

    # both pipe and fsdp genuinely shard the stacked stage params
    qkv = engine.state.params["block"]["qkv"]["kernel"]
    shard = qkv.sharding.shard_shape(qkv.shape)
    assert shard[0] == cfg.n_layers // 2                  # pipe
    assert int(np.prod(shard)) == int(np.prod(qkv.shape)) // 4  # + fsdp


def test_pipeline_loss_chunked_ce(devices):
    """The pipelined head honors loss_chunk (fused chunked CE) and still
    matches the dense single-program loss."""
    cfg = tiny_cfg(n_layers=4, loss_chunk=16)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(2).integers(0, 128, (8, 17)).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}

    import dataclasses
    dense_cfg = dataclasses.replace(cfg, loss_chunk=0)
    ref = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0),
                            dense_cfg, deterministic=True))
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4, num_micro=2)
    with jax.set_mesh(mesh):
        pl_loss = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(ref, pl_loss, rtol=1e-5)


def test_default_schedule_is_1f1b_with_gpipe_eval(devices):
    """1F1B is now the training default (the memory-bounded schedule is
    the one that matters at depth); the loss fn carries a GPipe eval
    companion so eval_batch never pays the custom_vjp's eager fwd+bwd.
    Train loss (1F1B) and eval loss (GPipe) must agree on the same
    deterministic batch."""
    cfg = tiny_cfg(n_layers=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=4, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=4,
                                        num_micro=4)
    assert hasattr(loss_fn, "eval_fn")
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 0.0}},  # frozen
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules())
    data = {"tokens": np.random.default_rng(1).integers(
        0, 128, (8, 33)).astype(np.int32)}
    train_loss = float(engine.train_batch(data)["loss"])
    eval_loss, _aux = engine.eval_batch(data)
    np.testing.assert_allclose(train_loss, float(eval_loss), rtol=1e-5)


def test_1f1b_deep_8_stage(devices):
    """1F1B at depth: 8 stages over the full 8-device mesh (1 layer per
    stage, 8 microbatches) — the regime the memory-bounded schedule
    exists for. Trains, and matches the dense loss on step 1."""
    cfg = tiny_cfg(n_layers=8)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshSpec(pipe=8, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=8,
                                        num_micro=8)
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=ds, mesh=mesh,
        partition_rules=gpt.gpt_pipeline_partition_rules())
    data = {"tokens": np.random.default_rng(0).integers(
        0, 128, (8, 33)).astype(np.int32)}
    # dense reference BEFORE training: the engine donates its state
    # buffers, which alias the init pytree
    dense = float(gpt.make_loss_fn(cfg)(params, data,
                                        jax.random.PRNGKey(0)))
    first = float(engine.train_batch(data)["loss"])
    np.testing.assert_allclose(first, dense, rtol=2e-2)
    losses = [float(engine.train_batch(data)["loss"]) for _ in range(10)]
    assert losses[-1] < first - 0.3, (first, losses)
