"""Continuous-batching serving tests: paged-cache allocator unit tests,
greedy token parity vs the static engine, staggered arrivals joining a
running decode batch, and eviction/requeue on cache exhaustion
(tentpole: inference/paged_cache.py + inference/serving.py; analog of
vLLM's PagedAttention + Orca iteration-level scheduling over the
reference's static KV-cache workspace)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.paged_cache import CacheExhausted, PagedKVCache
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


# ---------------------------------------------------------------------------
# allocator unit tests
# ---------------------------------------------------------------------------

def test_paged_allocator_alloc_append_free(devices):
    cfg, _ = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=6)
    assert c.free_blocks == 6 and c.used_blocks == 0
    c.allocate(0, 5)                     # 2 blocks
    assert c.free_blocks == 4 and c.used_blocks == 2
    assert (c.tables[0, :2] > 0).all()   # block 0 is the reserved trash
    c.advance(0, 5)
    c.ensure_capacity(0, 8)              # still inside block 2
    assert c.used_blocks == 2
    c.ensure_capacity(0, 9)              # crosses into a third block
    assert c.used_blocks == 3
    c.allocate(1, 4)
    assert c.free_blocks == 2
    c.free(0)
    assert c.free_blocks == 5 and not c.active[0]
    assert (c.tables[0] == 0).all() and c.lengths[0] == 0
    # freed blocks are reusable
    c.allocate(0, 20)                    # 5 blocks
    assert c.free_blocks == 0


def test_paged_allocator_exhaustion_and_watermark(devices):
    cfg, _ = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=3,
                     watermark=1)
    with pytest.raises(CacheExhausted):
        c.allocate(0, 16)                # 4 blocks > 3 free
    c.allocate(0, 12)
    with pytest.raises(CacheExhausted):
        c.ensure_capacity(0, 13)         # free list empty
    # admission watermark: 3 free again after free(), but 1 is reserved
    c.free(0)
    assert c.can_admit(8) and not c.can_admit(12)


def test_paged_allocator_hardening_and_stats(devices):
    """Hardened bookkeeping in the DEFAULT (prefix-off) mode: free() is
    idempotent, double-free/foreign block ids raise instead of silently
    corrupting the pool, re-allocating an occupied slot raises, and
    stats() reports block states + fragmentation for bench rows."""
    cfg, _ = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=6)
    c.allocate(0, 5)                     # 2 blocks, 5 tokens pending
    with pytest.raises(ValueError, match="already allocated"):
        c.allocate(0, 4)
    c.advance(0, 5)
    s = c.stats()
    assert s["used_blocks"] == 2 and s["free_blocks"] == 4
    assert s["held_blocks"] == 2
    assert s["shared_blocks"] == 0 and s["cached_blocks"] == 0
    assert s["fragmentation"] == round(1 - 5 / 8, 4)  # 5 of 8 written
    bid = c._owned[0][0]
    c.free(0)
    c.free(0)                            # idempotent: freeing twice is ok
    assert c.free_blocks == 6 and c.stats()["fragmentation"] == 0.0
    with pytest.raises(ValueError, match="double free"):
        c._release(bid)
    with pytest.raises(ValueError, match="foreign block"):
        c._release(0)                    # the reserved trash block
    with pytest.raises(ValueError, match="out of range"):
        c.allocate(5, 4)


def test_paged_cache_hbm_budget_watermark(devices):
    """num_blocks derives from an HBM budget via the per-token cache
    cost, and the usage accounting scales with tokens in flight."""
    cfg, _ = tiny()
    per_tok = gpt.kv_bytes_per_token(cfg, jnp.float32)
    budget = per_tok * 4 * 10            # exactly 10 4-token blocks
    # kv_quant pinned off: this pins the FP pool's budget arithmetic
    # (the int8 layout's budget math lives in test_kv_quant.py)
    c = PagedKVCache(cfg, num_slots=2, block_size=4,
                     hbm_budget_bytes=budget, dtype=jnp.float32,
                     kv_quant="off")
    assert c.free_blocks == 10
    c.allocate(0, 6)
    assert c.used_block_bytes() == 2 * 4 * per_tok
    # static equivalent for 2 slots reserves 2 * S_max tokens
    assert c.static_equivalent_bytes(2) == 2 * 64 * per_tok
    with pytest.raises(ValueError):
        PagedKVCache(cfg, num_slots=1, block_size=4, hbm_budget_bytes=1)


# ---------------------------------------------------------------------------
# greedy token parity: paged + continuous batching == static generate
# ---------------------------------------------------------------------------

def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


def test_serving_greedy_parity(devices):
    """Mixed prompt lengths through the paged continuous-batching path
    reproduce static-batch generate token-for-token (zero tolerance)."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 9, 12, 3))
    refs = _solo_refs(eng, prompts, 6)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        prefill_chunk=8)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)
                   for i, p in enumerate(prompts)])
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert srv.stats["completed"] == 4
    # decode batching really happened (two requests in one decode step)
    assert srv.stats["peak_occupancy"] > 1


def test_serving_parity_rotary_gqa_window(devices):
    """The paged decode composes with the full serving feature stack:
    rotary positions, grouped KV heads, sliding-window masking."""
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, rotary_dim=4, use_wpe=False,
                              n_kv_heads=2, attn_window=6)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((4, 10, 7), seed=7)
    refs = _solo_refs(eng, prompts, 5)
    srv = ServingEngine(eng, num_slots=3, block_size=4, num_blocks=30,
                        prefill_chunk=4)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=5)
                   for i, p in enumerate(prompts)])
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    # GQA pool really is grouped: kv-head dim == 2
    assert srv.cache.k.shape[3] == 2


def test_serving_prefill_chunking_long_prompt(devices):
    """A prompt longer than the chunk width prefills across iterations
    and still matches the static one-shot prefill."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((23,), seed=3)
    refs = _solo_refs(eng, prompts, 4)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=20,
                        prefill_chunk=5)
    out = srv.run([ServeRequest(rid=0, prompt=prompts[0],
                                max_new_tokens=4)])
    np.testing.assert_array_equal(out[0], refs[0])
    assert srv.stats["prefill_chunks"] == 5  # ceil(23/5)


def test_serving_eos_stop(devices):
    """Per-request stop conditions: an eos hit frees the slot early."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p = prompts_of((6,), seed=2)[0]
    ref = _solo_refs(eng, [p], 8)[0]
    eos = int(ref[len(p) + 2])           # a token generate() really emits
    # serving stops at the FIRST eos occurrence in the generated region
    first = len(p) + int(np.argmax(ref[len(p):] == eos))
    srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=12)
    out = srv.run([ServeRequest(rid=0, prompt=p, max_new_tokens=8,
                                eos_id=eos)])
    assert len(out[0]) < len(ref)        # it actually stopped early
    np.testing.assert_array_equal(out[0], ref[:first + 1])


# ---------------------------------------------------------------------------
# scheduler: staggered arrivals, admission, eviction
# ---------------------------------------------------------------------------

def test_serving_staggered_arrival_joins_running_batch(devices):
    """A request arriving mid-decode joins the running batch (occupancy
    2) instead of waiting for the first to drain — the continuous-
    batching acceptance gate — and both outputs stay parity-exact."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((6, 8), seed=11)
    ref1 = _solo_refs(eng, [p1], 12)[0]
    ref2 = _solo_refs(eng, [p2], 6)[0]
    # spec and the decode horizon pinned to the one-token-per-step
    # cadence: the step-4 arrival must catch r1 mid-decode (spec timing
    # and N>1 cadence have their own suites)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        prefill_chunk=8, spec_decode=False,
                        decode_horizon=1)
    srv.submit(ServeRequest(rid="r1", prompt=p1, max_new_tokens=12), now=0)
    occ = []
    step = 0
    while srv.busy:
        if step == 4:                    # r1 is mid-decode by now
            srv.submit(ServeRequest(rid="r2", prompt=p2,
                                    max_new_tokens=6), now=step)
        occ.append(srv.step(step))
        step += 1
    assert max(occ) == 2                 # r2 decoded alongside r1
    done = {r.rid: r for r in srv.finished}
    np.testing.assert_array_equal(done["r1"].tokens, ref1)
    np.testing.assert_array_equal(done["r2"].tokens, ref2)
    # r2 produced its first token before r1 finished
    assert done["r2"].first_token_at < done["r1"].finished_at


def test_serving_admission_blocks_when_cache_full(devices):
    """Admission control: with only enough blocks for one request, the
    second waits in the queue (no slot claim, no OOM) and runs after."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((8, 8), seed=4)
    refs = [_solo_refs(eng, [p], 4)[0] for p in (p1, p2)]
    # 5 blocks: request needs 2(prompt)+1(decode); watermark=2 keeps the
    # second request queued until the first frees its blocks
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=5)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=4)
                   for i, p in enumerate((p1, p2))])
    assert srv.stats["peak_occupancy"] == 1
    for i in range(2):
        np.testing.assert_array_equal(out[i], refs[i])


def test_serving_eviction_requeue_parity(devices):
    """Cache exhaustion mid-decode evicts the youngest request and
    requeues it (recompute-on-resume) — outputs still parity-exact."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)
    ref1 = _solo_refs(eng, [p1], 12)[0]
    ref2 = _solo_refs(eng, [p2], 10)[0]
    # deliberately tight pool + zero watermark: both admit, then decode
    # growth exhausts the free list and forces a preemption
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7)
    srv.cache.watermark = 0
    out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                   ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
    assert srv.stats["evictions"] >= 1
    np.testing.assert_array_equal(out["a"], ref1)
    np.testing.assert_array_equal(out["b"], ref2)


def test_serving_int8_compose(devices):
    """Weight-only int8 engines serve through the paged path (the
    DS_INT8_FUSED dense entries carry {"q","scale"} instead of
    {"kernel"}): parity against the SAME quantized engine's static
    generate."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.int8)
    assert eng.quantized
    prompts = prompts_of((6, 9), seed=13)
    refs = _solo_refs(eng, prompts, 5)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=20)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=5)
                   for i, p in enumerate(prompts)])
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


def test_serving_compile_count_contract(devices):
    """The serving perf contract as an executable assert: steady state
    is exactly TWO compiled programs (_prefill_slot, _decode_slots) and
    ZERO recompiles across admission, chunked prefill, eviction and
    requeue.  The warmup run compiles everything once (including the
    per-slot eager emit slices — both slots see traffic); the second,
    identical workload must then compile NOTHING."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)

    def run_workload():
        # tight pool + zero watermark: both requests admit, decode
        # growth exhausts the free list, the youngest evicts + requeues.
        # spec and the decode horizon pinned off: this pins the PLAIN
        # decode program contract (the spec twin lives in
        # test_spec_serving.py, the _decode_horizon family in
        # test_horizon.py)
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                            prefill_chunk=8, spec_decode=False,
                            decode_horizon=1)
        srv.cache.watermark = 0
        out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                       ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return srv, out

    srv, warm_out = run_workload()
    assert srv.stats["evictions"] >= 1     # the workload really preempts
    # exactly two compiled serving programs after warmup — one prefill
    # (chunks are padded to prefill_chunk, so ONE shape) and one decode.
    # Under DS_KV_QUANT=int8 / DS_LORA_SERVE=on the active set is the
    # _q / _l / _ql jit twin family; the program COUNT contract is
    # identical in every mode
    sfx = ("_q" if srv.kv_quant == "int8" else "") + \
          ("_l" if srv.lora_serve else "")
    pf = getattr(eng, "_prefill_slot" + sfx)
    dc = getattr(eng, "_decode_slots" + sfx)
    n_prefill = cache_size(pf)
    n_decode = cache_size(dc)
    if n_prefill is not None:
        assert (n_prefill, n_decode) == (1, 1), (
            f"serving steady state fragmented: prefill={n_prefill} "
            f"decode={n_decode} compiled programs (expected 1+1)")

    watch = CompileWatch(max_compiles=0, label="serving steady state")
    watch.wrap(pf)
    watch.wrap(dc)
    with watch:                            # raises RecompileError on exit
        srv2, out = run_workload()         # if anything compiled
    assert srv2.stats["evictions"] >= 1
    for rid in ("a", "b"):                 # still the right tokens
        np.testing.assert_array_equal(out[rid], warm_out[rid])
    if n_prefill is not None:
        assert cache_size(pf) == 1
        assert cache_size(dc) == 1


def test_serving_rejects_oversized_request(devices):
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=8)
    with pytest.raises(ValueError, match="max_seq_len"):
        srv.submit(ServeRequest(rid=0, prompt=np.ones(60, np.int32),
                                max_new_tokens=30))


def test_serving_wall_clock_latency_stamps_share_one_clock(devices):
    """run(wall_clock=True) stamps submission with the SAME clock as
    token emission — submitted_at <= first_token_at <= finished_at, all
    positive perf_counter instants, so latency percentiles derived from
    the stamps are meaningful (the skew bug: submit stamped 0.0 while
    tokens got perf_counter values, making TTFT equal absolute time)."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24)
    prompts = prompts_of((5, 7), seed=21)
    srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=4)
             for i, p in enumerate(prompts)], wall_clock=True)
    for r in srv.finished:
        assert r.submitted_at > 0.0              # not the 0.0 sentinel
        assert r.submitted_at <= r.first_token_at <= r.finished_at
        # a sane TTFT: well under a minute, not "seconds since boot"
        assert r.first_token_at - r.submitted_at < 60.0
        assert all(t >= r.submitted_at for t in r.token_times)


def test_serving_non_drain_raises_degraded_with_partial_results(devices):
    """run() hitting max_steps attaches everything finished so far plus
    an in-flight snapshot instead of discarding it."""
    from deepspeed_tpu.inference.serving import DegradedError
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((5, 6), seed=17)
    ref2 = _solo_refs(eng, [p2], 2)[0]
    # horizon pinned: the max_steps=5 non-drain budget is calibrated to
    # one token per step (a fused horizon would drain inside it)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                        decode_horizon=1)
    with pytest.raises(DegradedError, match="did not drain") as ei:
        srv.run([ServeRequest(rid="slowpoke", prompt=p1,
                              max_new_tokens=30),
                 ServeRequest(rid="quick", prompt=p2, max_new_tokens=2)],
                max_steps=5)
    e = ei.value
    np.testing.assert_array_equal(e.results["quick"], ref2)
    assert [p["rid"] for p in e.pending] == ["slowpoke"]
    assert e.pending[0]["generated"] > 0         # its work is visible
    assert e.stats["steps"] == 6                 # ran to the cap, then raised
