"""Ulysses (all-to-all) sequence parallelism tests on the 8-device CPU
mesh — parity with dense attention and with ring attention
(the sp capability family; SURVEY §2.2/§5 long-context)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.attention.flash import mha_reference
from deepspeed_tpu.ops.attention.ulysses import ulysses_attention
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _qkv(B=2, S=64, H=8, D=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(devices, causal):
    q, k, v = _qkv()
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_grads_match_dense(devices):
    q, k, v = _qkv(B=1, S=32, H=8, D=8)
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    g_u = jax.grad(lambda q, k, v: jnp.sum(
        ulysses_attention(q, k, v, mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_u, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_ulysses_with_data_parallel_axes(devices):
    q, k, v = _qkv(S=32, H=4)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_gpt_trains(devices):
    """GPT with sp_impl='ulysses' through the engine: loss parity with the
    ring implementation and finite training steps."""
    from deepspeed_tpu.models import gpt
    mesh = make_mesh(MeshSpec(data=2, sequence=4))

    def build(impl):
        cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                            d_model=32, max_seq_len=64,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32, sequence_parallel=True,
                            sp_impl=impl, mesh=mesh)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 4,
                    "mesh": {"data_parallel_size": 2,
                             "sequence_parallel_size": 4},
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh)
        return eng

    r = np.random.default_rng(0)
    data = {"tokens": r.integers(0, 128, (4, 33)).astype(np.int32)}
    e_u = build("ulysses")
    e_r = build("ring")
    for _ in range(3):
        lu = float(e_u.train_batch(data)["loss"])
        lr_ = float(e_r.train_batch(data)["loss"])
        np.testing.assert_allclose(lu, lr_, rtol=1e-4)
    assert np.isfinite(lu)


def test_ulysses_gqa_matches_dense(devices):
    """GQA under Ulysses: q heads 8, kv heads 4, sp=4 — matches the
    dense grouped reference."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    B, S, H, Hkv, D = 1, 64, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_packed_segments_matches_dense(devices):
    """segment_ids through the all-to-all layout: full rows are local
    after the seq->head swap, so packing must match the dense kernel."""
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    q, k, v = _qkv(B=2, S=64, H=8, D=16)
    segs = jnp.asarray(np.repeat(np.arange(4), 16)[None].repeat(2, 0),
                       jnp.int32)
    out = ulysses_attention(q, k, v, mesh, causal=True, segment_ids=segs)
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_window_matches_dense(devices):
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    q, k, v = _qkv(B=2, S=64, H=8, D=16)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=16)
    ref = mha_reference(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_kv_mask_matches_dense(devices):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = _qkv(B=2, S=64, H=8, D=16)
    r = np.random.default_rng(3)
    mask = jnp.asarray((r.random((2, 64)) > 0.25).astype(np.float32))
    out = ulysses_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    ref = mha_reference(q, k, v, causal=True, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_packed_grads_match_dense(devices):
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    q, k, v = _qkv(B=1, S=32, H=8, D=8)
    segs = jnp.asarray(np.repeat(np.arange(2), 16)[None], jnp.int32)
    g_u = jax.grad(lambda q, k, v: jnp.sum(ulysses_attention(
        q, k, v, mesh, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_u, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_ulysses_packed_gpt_trains(devices):
    """End-to-end: a PACKED batch (pack_documents) through a GPT with
    sp_impl='ulysses' on a data x sequence mesh — loss parity with the
    unsharded model, finite steps. models/gpt.py's SP guard now narrows
    to ring-only."""
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.runtime.dataloader import pack_documents

    r = np.random.default_rng(0)
    docs = [r.integers(0, 128, ln).astype(np.int32)
            for ln in (20, 30, 15, 33, 9, 22)]
    packed = pack_documents(docs, seq_len=65, pad_token=0)
    packed = {k_: v_[:2] for k_, v_ in packed.items()}
    assert packed["tokens"].shape[0] >= 2

    mesh = make_mesh(MeshSpec(data=2, sequence=4))

    ref_mesh = make_mesh(MeshSpec(data=2), devices=jax.devices()[:2])

    def build(sp):
        cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                            d_model=32, max_seq_len=64,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32, sequence_parallel=sp,
                            sp_impl="ulysses", mesh=mesh if sp else None)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 2,
                    "mesh": ({"data_parallel_size": 2,
                              "sequence_parallel_size": 4} if sp
                             else {"data_parallel_size": 2}),
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh if sp else ref_mesh)
        return eng

    e_sp = build(True)
    e_ref = build(False)
    for _ in range(2):
        l_sp = float(e_sp.train_batch(packed)["loss"])
        l_ref = float(e_ref.train_batch(packed)["loss"])
        np.testing.assert_allclose(l_sp, l_ref, rtol=1e-4)
    assert np.isfinite(l_sp)



def test_ulysses_window_masked_impl_matches_dense(devices):
    """window_impl='masked' (the PARITY.md quarantine fallback) must
    thread through the SP path too — a config that requests it under
    Ulysses may never silently compile the banded kernel."""
    from deepspeed_tpu.ops.attention.flash import mha_reference
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    q, k, v = _qkv(B=2, S=64, H=8, D=16)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=16,
                            window_impl="masked")
    ref = mha_reference(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
