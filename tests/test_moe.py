"""MoE tests: gating semantics, capacity/dropping, l_aux, dispatch/combine
consistency, expert-parallel sharding, MoE-GPT training
(ref: tests/unit/test_moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.moe.experts import ffn_expert_fn, init_ffn_experts
from deepspeed_tpu.moe.layer import MoE, MoEConfig, moe_partition_rules
from deepspeed_tpu.moe.sharded_moe import (TopKGate, moe_layer_apply,
                                           top1gating, top2gating)


def _logits(G=2, S=16, E=4, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (G, S, E))


def test_top1_dispatch_is_onehot(devices):
    out = top1gating(_logits(), capacity_factor=2.0)
    d = np.asarray(out.dispatch)
    # every non-dropped token goes to exactly one (expert, slot)
    per_token = d.reshape(d.shape[0], d.shape[1], -1).sum(-1)
    assert set(np.unique(per_token)) <= {0.0, 1.0}


def test_top1_capacity_enforced(devices):
    """With cf=1, per-expert tokens <= ceil(S/E)."""
    out = top1gating(_logits(S=32, E=4), capacity_factor=1.0, min_capacity=1)
    d = np.asarray(out.dispatch)  # [G,S,E,C]
    assert d.shape[-1] == 8  # ceil(32/4 * 1.0)
    per_expert = d.sum(axis=(1, 3))  # [G,E]
    assert per_expert.max() <= 8
    # each (expert, slot) used at most once per group
    slot_use = d.sum(axis=1)  # [G,E,C]
    assert slot_use.max() <= 1


def test_top1_no_drop(devices):
    out = top1gating(_logits(), capacity_factor=1.0, drop_tokens=False)
    d = np.asarray(out.dispatch)
    per_token = d.reshape(d.shape[0], d.shape[1], -1).sum(-1)
    assert (per_token == 1.0).all()  # nothing dropped


def test_top1_aux_loss_balanced_vs_skewed(devices):
    """l_aux is ~1 for uniform routing and larger for skewed routing."""
    E = 4
    uniform = jnp.zeros((1, 64, E))
    skew = jnp.zeros((1, 64, E)).at[..., 0].set(5.0)
    l_uniform = float(top1gating(uniform, 2.0).l_aux)
    l_skew = float(top1gating(skew, 2.0).l_aux)
    assert l_skew > l_uniform


def test_top2_two_experts_per_token(devices):
    out = top2gating(_logits(S=8, E=4), capacity_factor=4.0, min_capacity=16)
    d = np.asarray(out.dispatch)
    per_token = d.reshape(d.shape[0], d.shape[1], -1).sum(-1)
    assert per_token.max() == 2.0
    # combine weights normalized: sum over (E,C) ~ 1 for kept tokens
    c = np.asarray(out.combine).reshape(d.shape[0], d.shape[1], -1).sum(-1)
    kept = per_token == 2.0
    np.testing.assert_allclose(c[kept], 1.0, rtol=1e-5)


def test_moe_layer_identity_routing(devices):
    """With identity experts, MoE output == gate1 * x for kept tokens."""
    G, S, d_model, E = 2, 8, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (G, S, d_model))
    gate = TopKGate(k=1, capacity_factor=4.0, min_capacity=8)
    gp = TopKGate.init_params(jax.random.PRNGKey(1), d_model, E)

    def identity_expert(params, tokens):
        return tokens

    y, l_aux, counts = moe_layer_apply(gate, gp, {}, identity_expert, x)
    out = gate(gp, x)
    gate1 = np.asarray(out.combine).reshape(G, S, -1).sum(-1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * gate1[..., None],
                               rtol=1e-4, atol=1e-5)
    assert float(counts.sum()) == G * S


def test_moe_facade_and_residual(devices):
    cfg = MoEConfig(num_experts=4, k=1, capacity_factor=2.0, use_residual=True)
    moe = MoE(d_model=16, d_ff=32, cfg=cfg)
    params = moe.init_params(jax.random.PRNGKey(0))
    assert "residual_mlp" in params and "coefficient" in params
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, l_aux, counts = moe.apply(params, x)
    assert y.shape == x.shape
    assert np.isfinite(float(l_aux))


def test_expert_parallel_sharding(devices):
    """Expert stacks physically shard over the data axes."""
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    from deepspeed_tpu.parallel.sharding import param_specs, to_named
    mesh = make_mesh(MeshSpec(data=8))
    params = {"experts": init_ffn_experts(jax.random.PRNGKey(0), 8, 16, 32)}
    specs = to_named(param_specs(params, mesh, zero_stage=0,
                                 rules=moe_partition_rules()), mesh)
    placed = jax.device_put(params, specs)
    wi = placed["experts"]["wi"]["kernel"]
    assert wi.sharding.shard_shape(wi.shape)[0] == 1  # 8 experts / 8 devices


def test_moe_gpt_trains(devices):
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=32, max_seq_len=32,
        num_experts=8, moe_k=1, capacity_factor=2.0,
        use_flash_attention=False, remat=False, dtype=jnp.float32)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params, config=ds,
        partition_rules=moe_gpt.moe_gpt_partition_rules())
    data = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": data})["loss"])
              for _ in range(12)]
    assert losses[-1] < losses[0] - 0.5, losses
    # expert kernels sharded over data on the E dim
    wi = engine.state.params["block"]["moe"]["experts"]["wi"]["kernel"]
    assert wi.sharding.shard_shape(wi.shape)[1] == cfg.num_experts // 8


def test_top2_matches_top1_structure(devices):
    """top-2 with k collapsed still produces valid slot assignment."""
    out = top2gating(_logits(S=16, E=2), capacity_factor=1.0, min_capacity=4)
    d = np.asarray(out.dispatch)
    slot_use = d.sum(axis=1)
    assert slot_use.max() <= 1


def test_moe_loss_chunked_parity(devices):
    import dataclasses
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=2, d_model=32, max_seq_len=32,
        dtype=jnp.float32, use_flash_attention=False, remat=False,
        num_experts=4, moe_k=1)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(11).integers(0, 128, (4, 17)), jnp.int32)}
    rng = jax.random.PRNGKey(1)
    dense = moe_gpt.loss_fn(params, batch, rng, cfg, train=False)
    chunked = moe_gpt.loss_fn(params, batch, rng,
                              dataclasses.replace(cfg, loss_chunk=16),
                              train=False)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_moe_gpt_with_sequence_parallel(devices):
    """MoE x SP composition: expert dispatch with the token dim sharded
    over 'sequence' (Ulysses attention) — loss parity with the same
    model unsharded."""
    import deepspeed_tpu
    from deepspeed_tpu.models import moe_gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ref_mesh = make_mesh(MeshSpec(data=2), devices=jax.devices()[:2])

    def build(sp):
        cfg = moe_gpt.MoEGPTConfig(
            vocab_size=128, n_layers=2, n_heads=4, d_model=32,
            max_seq_len=32, num_experts=4, moe_k=1, capacity_factor=2.0,
            use_flash_attention=False, remat=False, dtype=jnp.float32,
            sequence_parallel=sp, sp_impl="ulysses",
            mesh=mesh if sp else None)
        params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=moe_gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 2,
                    "mesh": ({"data_parallel_size": 2,
                              "sequence_parallel_size": 4} if sp
                             else {"data_parallel_size": 2}),
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh if sp else ref_mesh,
            partition_rules=moe_gpt.moe_gpt_partition_rules())
        return eng

    data = {"tokens": np.random.default_rng(0).integers(
        0, 128, (2, 33)).astype(np.int32)}
    e_sp = build(True)
    e_ref = build(False)
    for _ in range(2):
        l_sp = float(e_sp.train_batch(data)["loss"])
        l_ref = float(e_ref.train_batch(data)["loss"])
        np.testing.assert_allclose(l_sp, l_ref, rtol=1e-4)
    assert np.isfinite(l_sp)


def test_moe_swiglu_expert_dialect(devices):
    """MoEGPTConfig with the llama dialect: swiglu expert stacks (wg
    present, biases dropped) train and decrease the loss; num_params
    stays exact."""
    from deepspeed_tpu.models import moe_gpt
    import deepspeed_tpu
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=32, max_seq_len=32,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
        num_experts=4, moe_k=2, capacity_factor=2.0,
        norm="rmsnorm", activation="swiglu", use_bias=False,
        use_wpe=False, rotary_dim=8, tie_embeddings=False)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    ex = params["block"]["moe"]["experts"]
    assert "wg" in ex and "bias" not in ex["wi"]
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == moe_gpt.num_params(cfg), (actual,
                                               moe_gpt.num_params(cfg))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
                "steps_per_print": 1000})
    toks = np.random.default_rng(0).integers(0, 128, (8, 33)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0] - 0.2, losses


def test_moe_inference_matches_training_eval_forward(devices):
    """The inference engine's dense no-drop MoE mix must serve the SAME
    logits as the training model's eval forward — incl. the top-1 raw-
    probability weighting convention (GShard top1gating weighs by p1,
    NOT a renormalized 1.0)."""
    import dataclasses
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=32, max_seq_len=32,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
        num_experts=4, moe_k=1)
    params = moe_gpt.init_params(jax.random.PRNGKey(3), cfg)
    toks = np.random.default_rng(4).integers(0, 128, (2, 10)).astype(np.int32)
    # no-drop eval reference from the training stack
    cfg_eval = dataclasses.replace(
        cfg, eval_capacity_factor=2.0 * cfg.num_experts)
    ref, _aux = moe_gpt.forward(params, jnp.asarray(toks), cfg_eval,
                                train=False)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    got = eng.forward(toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_packed_batch_segments_and_mask(devices):
    """Packed batches through the MoE model: segment_ids isolate
    documents (doc-1 logits invariant to doc-2 content), and loss_mask
    drives a masked mean. Without segment_ids the same perturbation DOES
    leak — proving the mask is live."""
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=64, n_layers=2, n_heads=2, d_model=16, max_seq_len=16,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
        num_experts=2, moe_k=1, eval_capacity_factor=4.0)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(0)
    toks = r.integers(0, 64, (1, 16)).astype(np.int32)
    toks2 = toks.copy()
    toks2[0, :8] = r.integers(0, 64, 8)          # perturb document 1
    segs = np.repeat([[0, 1]], 8, axis=1).reshape(1, 16).astype(np.int32)
    poss = np.concatenate([np.arange(8), np.arange(8)])[None].astype(np.int32)

    def logits(t, with_segs):
        out, _ = moe_gpt.forward(
            params, jnp.asarray(t), cfg, train=False,
            positions=jnp.asarray(poss),
            segment_ids=jnp.asarray(segs) if with_segs else None)
        return np.asarray(out)

    # document 2 (causally AFTER doc 1) must be isolated by segment_ids
    iso = logits(toks, True)[0, 8:]
    iso2 = logits(toks2, True)[0, 8:]
    np.testing.assert_allclose(iso, iso2, rtol=1e-6, atol=1e-6)
    leak = logits(toks, False)[0, 8:]
    leak2 = logits(toks2, False)[0, 8:]
    assert np.abs(leak - leak2).max() > 1e-4   # without segs it leaks

    # loss_mask: zeroing all but token j reduces to that token's NLL
    batch = {"tokens": jnp.asarray(toks),
             "segment_ids": jnp.asarray(segs),
             "positions": jnp.asarray(poss)}
    mask = np.zeros((1, 15), np.float32)
    mask[0, 3] = 1.0
    import dataclasses
    cfg0 = dataclasses.replace(cfg, aux_loss_weight=0.0)
    loss = float(moe_gpt.loss_fn(
        params, {**batch, "loss_mask": jnp.asarray(mask)},
        jax.random.PRNGKey(0), cfg0, train=False))
    out, _ = moe_gpt.forward(params, jnp.asarray(toks[:, :-1]), cfg0,
                             train=False,
                             positions=jnp.asarray(poss[:, :-1]),
                             segment_ids=jnp.asarray(segs[:, :-1]))
    logp = jax.nn.log_softmax(np.asarray(out)[0, 3].astype(np.float64))
    np.testing.assert_allclose(loss, -logp[toks[0, 4]], rtol=1e-5)


def test_int8_moe_inference(devices):
    """Weight-only int8 composes with the MoE decode path (expert
    stacks quantize; the eval mix dequantizes per matmul)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=32, max_seq_len=32,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
        num_experts=4, moe_k=2)
    params = moe_gpt.init_params(jax.random.PRNGKey(1), cfg)
    ref = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    q = InferenceEngine(config=cfg, params=params, dtype=jnp.int8)
    assert q.params["block"]["moe"]["experts"]["wi"]["q"].dtype == jnp.int8
    toks = np.random.default_rng(2).integers(0, 128, (2, 8)).astype(np.int32)
    lo = np.asarray(ref.forward(toks))
    lq = np.asarray(q.forward(toks))
    assert np.corrcoef(lo.ravel(), lq.ravel())[0, 1] > 0.995
