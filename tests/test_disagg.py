"""Disaggregated prefill/decode fleet suite (tentpole: replica roles +
fault-tolerant KV migration, docs/ROBUSTNESS.md).

Layers:
  1. migration parity — a 1-prefill/1-decode fleet serves every
     request token-identically to a solo greedy run, with every
     request's KV migrating through the CRC-verified host channel
     (``router_migrations`` == requests, zero fallbacks) and both
     pools' block accounting balancing afterwards;
  2. the degradation ladder — a fault at each ``router.migrate_*``
     site (transient, CRC corruption, crash on either endpoint)
     degrades that request to a cold re-prefill on the decode side
     with parity intact, no parked entries, no ``_in_transfer``
     leaks, and no orphaned host-pool keys (DS016);
  3. retire/breaker racing an in-flight migration — a retire settles
     pending handoffs through the migrate path first; a crash mid-
     migration drains the victim and the request lands COLD on a
     survivor with parity; the last decode-capable replica refuses to
     retire;
  4. the compile contract — migration gather/scatter lanes pre-warm at
     router construction, so a migrating steady state compiles
     nothing (CompileWatch(0)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import RETIRED, ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils.compile_guard import CompileWatch
from deepspeed_tpu.utils.faults import Fault, FaultInjector

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_fleet(eng, n=2, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return [ServingEngine(eng, **defaults) for _ in range(n)]


def mk_reqs(prompts, n=6, **kw):
    return [ServeRequest(rid=i, prompt=p, max_new_tokens=n, **kw)
            for i, p in enumerate(prompts)]


def assert_pools_clean(router):
    """Both sides' block accounting balances after the fleet drains:
    nothing parked, nothing mid-transfer, no orphaned host keys —
    the DS016 resource-pairing invariant, observed end to end."""
    for rep in router.replicas:
        st = rep.srv.cache.stats()
        assert st["parked_blocks"] == 0, (rep.idx, st)
        assert not rep.srv.cache._in_transfer, rep.idx
        assert st["free_blocks"] + st["cached_blocks"] \
            == st["num_blocks"], (rep.idx, st)
    assert len(router._mig_pool) == 0, "leaked host staging keys"


# ---------------------------------------------------------------------------
# migration parity
# ---------------------------------------------------------------------------

def test_disagg_migration_parity(eng):
    """Every request prefills on the prefill replica, migrates its KV
    through the host channel, and resumes decode on the decode replica
    token-identically to a solo run — no re-prefill, no fallback."""
    prompts = prompts_of((6, 9, 12, 8))
    refs = _solo_refs(eng, prompts, 6)
    router = ReplicaRouter(mk_fleet(eng), roles=["prefill", "decode"],
                           telemetry=True)
    res = router.run(mk_reqs(prompts))
    for i, ref in enumerate(refs):
        assert np.array_equal(res[i], ref), f"rid {i} diverged"
    assert router.stats["migrations"] == len(prompts)
    assert router.stats["migration_fallbacks"] == 0
    assert_pools_clean(router)


def test_disagg_role_validation(eng):
    """Role vocabulary is closed and a fleet with prefill replicas
    needs somewhere to land migrations."""
    with pytest.raises(ValueError, match="role"):
        ReplicaRouter(mk_fleet(eng), roles=["prefill", "archon"])
    with pytest.raises(ValueError):
        ReplicaRouter(mk_fleet(eng), roles=["prefill", "prefill"])
    with pytest.raises(ValueError):
        ReplicaRouter(mk_fleet(eng), roles=["prefill"][:1] * 2)


# ---------------------------------------------------------------------------
# the degradation ladder, one rung per fault
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site,kind", [
    ("router.migrate_gather", "device_error"),
    ("router.migrate_scatter", "device_error"),
    ("router.migrate_corrupt", "cache_exhausted"),
    ("router.migrate_gather", "crash"),
    ("router.migrate_scatter", "crash"),
])
def test_migration_fault_degrades_cold_with_parity(eng, site, kind):
    """Any failure mid-migration — transient on either side, a REAL
    CRC32 mismatch from a flipped host byte, or a crash that breaks
    the acting endpoint — lands the request as a cold re-prefill with
    token parity, counted in ``migration_fallbacks``, and neither
    pool leaks a parked entry, an ``_in_transfer`` pairing, or a host
    staging key."""
    prompts = prompts_of((6, 9, 12, 8))
    refs = _solo_refs(eng, prompts, 6)
    # a crash breaks one endpoint, so give the fleet a survivor on
    # each side of the channel
    n, roles = (3, ["prefill", "decode", "decode"]) if kind == "crash" \
        else (2, ["prefill", "decode"])
    inj = FaultInjector([Fault(site=site, kind=kind, step=0, count=1)],
                        seed=0)
    router = ReplicaRouter(mk_fleet(eng, n=n), roles=roles, faults=inj,
                           telemetry=True)
    res = router.run(mk_reqs(prompts))
    for i, ref in enumerate(refs):
        assert np.array_equal(res[i], ref), f"rid {i} diverged under {site}"
    assert router.stats["migration_fallbacks"] >= 1
    assert inj.fired, "fault never reached the site"
    assert_pools_clean(router)


def test_migration_corrupt_is_detected_not_served(eng):
    """The corrupt rung flips a REAL stored byte: the per-array CRC32
    verify inside the landing (not the injector) must catch it — the
    fallback reason in the migrate trace event names the corruption,
    and the poisoned bytes never reach a pool."""
    prompts = prompts_of((8,), seed=3)
    refs = _solo_refs(eng, prompts, 6)
    inj = FaultInjector([Fault(site="router.migrate_corrupt",
                               kind="cache_exhausted", step=0, count=1)],
                        seed=0)
    router = ReplicaRouter(mk_fleet(eng), roles=["prefill", "decode"],
                           faults=inj, telemetry=True)
    res = router.run(mk_reqs(prompts))
    assert np.array_equal(res[0], refs[0])
    falls = [rec for rec in router.telemetry.tracer.records()
             if rec[1] == "migrate" and not (rec[5] or {}).get("ok")]
    assert falls and "CRC32" in str(falls[0][5].get("reason")), falls


# ---------------------------------------------------------------------------
# retire / breaker racing an in-flight migration
# ---------------------------------------------------------------------------

def test_retire_prefill_settles_handoffs_first(eng):
    """A retire of the prefill replica with handoffs parked settles
    them through the migrate path BEFORE retiring — the same
    discipline as ``abort_transfers`` — and the requests finish on
    the decode side with parity."""
    prompts = prompts_of((6, 9))
    refs = _solo_refs(eng, prompts, 6)
    fleet = mk_fleet(eng)
    router = ReplicaRouter(fleet, roles=["prefill", "decode"],
                           telemetry=True)
    for req in mk_reqs(prompts):
        router.submit(req)
    # advance the prefill replica BEHIND the router's back until at
    # least one finished prefill is parked as a handoff — the router
    # has not harvested it yet, so the retire races a real in-flight
    # hand-over
    for _ in range(16):
        fleet[0].step()
        if fleet[0].ready_handoffs():
            break
    assert fleet[0].ready_handoffs(), "no handoff materialized"
    router.retire_replica(0)
    assert router.replicas[0].health == RETIRED
    assert router.stats["migrations"] >= 1
    res = router.run(max_steps=500)
    for i, ref in enumerate(refs):
        assert np.array_equal(res[i], ref), f"rid {i} diverged"
    assert_pools_clean(router)


def test_breaker_break_mid_migration_lands_cold_on_survivor(eng):
    """A crash during the gather breaks the SOURCE replica: its drain
    resumes every in-flight request — including the one whose
    migration was cut — cold on a survivor, with token parity and
    balanced accounting on both pools (no leaked ``_in_transfer`` or
    parked entries)."""
    prompts = prompts_of((6, 9, 12, 8))
    refs = _solo_refs(eng, prompts, 6)
    inj = FaultInjector([Fault(site="router.migrate_gather",
                               kind="crash", step=0, count=1)], seed=0)
    router = ReplicaRouter(mk_fleet(eng, n=3),
                           roles=["prefill", "decode", "decode"],
                           faults=inj, telemetry=True)
    res = router.run(mk_reqs(prompts))
    for i, ref in enumerate(refs):
        assert np.array_equal(res[i], ref), f"rid {i} diverged"
    # the cut migration degraded cold: fallbacks counted, and the
    # broken prefill replica's pool released every block at drain
    assert router.stats["migration_fallbacks"] >= 1
    assert router.stats["breaker_trips"] >= 1
    assert_pools_clean(router)


def test_retire_last_decode_capable_refused(eng):
    """The fleet must always keep a migration landing zone: retiring
    the only decode-capable replica is refused outright."""
    router = ReplicaRouter(mk_fleet(eng), roles=["prefill", "decode"],
                           telemetry=True)
    with pytest.raises(ValueError, match="decode-capable"):
        router.retire_replica(1)
    # the prefill replica itself can retire (decode side survives)
    router.retire_replica(0)
    assert router.replicas[0].health == RETIRED


@pytest.mark.slow
def test_parked_jump_under_bursty_open_load(eng):
    """Regression: a cold re-dispatched request at the decode
    replica's queue head once deadlocked the fleet — the blocks it
    waited for were HELD by parked migrated-in chains queued BEHIND
    it, which only free by being served. Admission now lets a parked
    request jump a blocked head (docs/ROBUSTNESS.md); this bursty
    open-load trace drives that exact interleaving and must drain
    with per-request token parity."""
    lg = pytest.importorskip("tools.load_gen")
    entries = lg.make_requests(seed=1, mix="mixed",
                               phases=[(10, 0.2), (15, 0.5), (45, 0.2)],
                               vocab_size=128, max_prompt_len=40)
    router = ReplicaRouter(mk_fleet(eng, block_size=8, num_blocks=24),
                           roles=["prefill", "decode"], telemetry=True)
    res = lg.drive(router, entries, mode="open", include_tokens=True,
                   max_steps=3000)
    by_rid = {e["rid"]: e for e in entries}
    for rec in res["per_request"]:
        e = by_rid[rec["rid"]]
        ref = eng.generate(np.asarray(e["prompt"], np.int32)[None],
                           max_new_tokens=int(e["max_new_tokens"]))[0]
        assert rec["tokens"] == [int(t) for t in ref], rec["rid"]
    assert router.stats["migrations"] >= 1
    assert_pools_clean(router)


# ---------------------------------------------------------------------------
# compile contract
# ---------------------------------------------------------------------------

def test_disagg_compile_contract(eng):
    """Migration rides the SAME gather/scatter programs as the host
    tier, pre-warmed at router construction — a migrating fleet's
    steady state compiles nothing."""
    router = ReplicaRouter(mk_fleet(eng), roles=["prefill", "decode"],
                           telemetry=True)
    prompts = prompts_of((6, 9, 12, 8))
    refs = _solo_refs(eng, prompts, 6)
    router.run(mk_reqs(prompts_of((7, 10), seed=9)))   # warm batch
    watch = CompileWatch(max_compiles=0, label="disagg steady state")
    with watch:
        res = router.run(mk_reqs(prompts))
    for i, ref in enumerate(refs):
        assert np.array_equal(res[i], ref)
    assert router.stats["migrations"] >= len(prompts) + 2
    assert watch.compiles == 0
