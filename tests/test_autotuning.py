"""Autotuner tests (ref: tests/unit/test_autotuning.py — experiment
generation/pruning checks without full tuning jobs, plus a small real
tune run here since experiments are in-process)."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import (
    Autotuner, Experiment, GridSearchTuner, ModelBasedTuner, RandomTuner,
    ResourceManager)
from deepspeed_tpu.autotuning.cost_model import RidgeCostModel
from deepspeed_tpu.autotuning.utils import (
    canonical_name, deep_update, dict_to_feature, flatten, gen_combinations)
from tests.simple_model import random_batch, simple_model_loss, simple_model_params

HIDDEN = 16


def _autotuner(tmp_path, base_overrides=None, at_overrides=None):
    base = {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 10000,
        "autotuning": {"num_tuning_steps": 1, "zero_stages": [0, 1],
                       "tuner_type": "gridsearch"},
    }
    base.update(base_overrides or {})
    base["autotuning"].update(at_overrides or {})
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=1)
    return Autotuner(simple_model_loss, params, base,
                     make_batch=lambda bs: random_batch(bs, HIDDEN),
                     results_dir=str(tmp_path / "results"))


# ------------------------------------------------------------- utils

def test_gen_combinations():
    space = {"zero_optimization": {"stage": [0, 1]},
             "train_micro_batch_size_per_gpu": [1, 2, 4]}
    combos = gen_combinations(space)
    assert len(combos) == 6
    assert {"zero_optimization": {"stage": 1},
            "train_micro_batch_size_per_gpu": 4} in combos


def test_flatten_and_features():
    flat = flatten({"a": {"b": 2, "c": True}, "d": "x"})
    assert flat["a_b"] == 2 and flat["a_c"] is True
    feat = dict_to_feature(flat, ["a_b", "a_c", "d", "missing"])
    assert feat[0] == 2.0 and feat[1] == 1.0 and feat[3] == 0.0


def test_deep_update_no_mutation():
    base = {"zero_optimization": {"stage": 0}, "x": 1}
    out = deep_update(base, {"zero_optimization": {"stage": 3}})
    assert out["zero_optimization"]["stage"] == 3
    assert base["zero_optimization"]["stage"] == 0


def test_canonical_name():
    assert canonical_name({"zero_optimization": {"stage": 2},
                           "train_micro_batch_size_per_gpu": 4,
                           "gradient_accumulation_steps": 2}) == "z2_mbs4_gas2"


# --------------------------------------------------------- cost model

def test_ridge_cost_model_learns_quadratic():
    rng = np.random.default_rng(0)
    xs = rng.uniform(0, 4, (40, 2))
    ys = 3 * xs[:, 0] - xs[:, 1] ** 2 + 5
    m = RidgeCostModel(alpha=1e-6)
    m.fit(xs, ys)
    pred = m.predict(xs)
    assert float(np.max(np.abs(pred - ys))) < 0.1


# ------------------------------------------------------------- tuners

def _fake_rm(scores):
    """runner scores configs by mbs (bigger better) via lookup."""
    return ResourceManager(
        lambda cfg: scores[cfg["train_micro_batch_size_per_gpu"]])


def _exps(mbs_list):
    return [Experiment(f"mbs{m}", {"train_micro_batch_size_per_gpu": m,
                                   "zero_optimization": {"stage": 0}})
            for m in mbs_list]


@pytest.mark.parametrize("tuner_cls", [GridSearchTuner, RandomTuner,
                                       ModelBasedTuner])
def test_tuners_find_best(tuner_cls):
    scores = {1: 10.0, 2: 25.0, 4: 40.0, 8: 30.0}
    rm = _fake_rm(scores)
    tuner = tuner_cls(_exps(scores.keys()), rm, "throughput")
    n = tuner.tune(sample_size=1, n_trials=10)
    assert n == 4
    assert tuner.best_exp.ds_config["train_micro_batch_size_per_gpu"] == 4
    assert tuner.best_metric_val == 40.0


def test_tuner_early_stopping():
    scores = {m: 100.0 - m for m in [1, 2, 3, 4, 5, 6, 7, 8]}  # first is best
    rm = _fake_rm(scores)
    tuner = GridSearchTuner(_exps(scores.keys()), rm, "throughput")
    n = tuner.tune(sample_size=1, n_trials=100, early_stopping=3)
    assert n < 8  # stopped before exhausting the space


def test_failed_experiment_recorded():
    def runner(cfg):
        raise MemoryError("oom")
    rm = ResourceManager(runner)
    rm.schedule_experiments(_exps([1]))
    rm.run()
    assert rm.finished_experiments[0].error is not None
    assert rm.best() is None


# ----------------------------------------------------------- autotuner

def test_memory_model_pruning(tmp_path, devices):
    at = _autotuner(tmp_path)
    at.model_info_profile_run()
    assert at.model_info["num_params"] > 0
    m0 = at.get_instantiation_memory_required_per_gpu(0)
    m3 = at.get_instantiation_memory_required_per_gpu(3)
    assert m3 < m0  # sharding reduces per-chip state

    # per-stage state bytes follow the 12/4/6-per-param accounting
    n = at.model_info["num_params"]
    assert m0 == pytest.approx((12 + 4 + 4 + 2) * n)


def test_generate_experiments_respects_global_batch(tmp_path, devices):
    at = _autotuner(tmp_path, at_overrides={"micro_batch_sizes": [1, 2, 8]})
    exps = at._generate_experiments(zero_stage=0)
    dp = 8  # conftest virtual devices
    for e in exps:
        cfg = e.ds_config
        assert cfg["train_micro_batch_size_per_gpu"] * dp * \
            cfg["gradient_accumulation_steps"] == 16
    # mbs=8 -> 8*8=64 > 16 global: excluded
    assert all(e.ds_config["train_micro_batch_size_per_gpu"] != 8
               for e in exps)


def test_tune_end_to_end(tmp_path, devices):
    """Small real tune: builds engines in-process, writes optimal config
    (ref: autotuner.py:396 tune + ds_config_optimal output)."""
    at = _autotuner(tmp_path, at_overrides={"micro_batch_sizes": [1, 2]})
    best = at.tune()
    assert best is not None
    assert best["train_batch_size"] == 16
    opt_path = os.path.join(str(tmp_path / "results"), "ds_config_optimal.json")
    with open(opt_path) as f:
        saved = json.load(f)
    assert saved == best
    at.print_tuning_results()  # must not raise
    # experiment records were persisted
    assert any(f.endswith(".json") for f in os.listdir(tmp_path / "results"))


# ------------------------------------- subprocess experiment dispatch
# (VERDICT r4 #6: the reference schedules every experiment as its own
#  job with failure capture — ref: autotuning/scheduler.py:35 run_job,
#  :183 parse_results; here that is SubprocessRunner + classified
#  ExperimentError kinds)

import subprocess
import sys

from deepspeed_tpu.autotuning import ExperimentError, SubprocessRunner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_subprocess_runner_success_and_config_file():
    """Default mode: ds_config lands in a temp JSON whose path is argv[-1]
    (the reference's per-job materialized ds_config.json)."""
    code = ("import json,sys; cfg=json.load(open(sys.argv[1])); "
            "print(json.dumps({'metric': cfg['mbs'] * 2.0}))")
    r = SubprocessRunner([sys.executable, "-c", code], timeout_s=60)
    assert r({"mbs": 4}) == 8.0


def test_subprocess_runner_classifies_timeout():
    r = SubprocessRunner([sys.executable, "-c",
                          "import time; time.sleep(30)"], timeout_s=1)
    with pytest.raises(ExperimentError) as ei:
        r({})
    assert ei.value.kind == "timeout"


def test_subprocess_runner_classifies_oom():
    code = ("import sys; sys.stderr.write('RESOURCE_EXHAUSTED: failed to "
            "allocate 9.9G\\n'); sys.exit(1)")
    r = SubprocessRunner([sys.executable, "-c", code], timeout_s=60)
    with pytest.raises(ExperimentError) as ei:
        r({})
    assert ei.value.kind == "oom"


def test_subprocess_runner_failures_dont_kill_the_sweep():
    """A hung + an OOMing + a healthy experiment: the loop finishes,
    records the two classified losses, and best() is the survivor."""
    flaky = {"hang": "import time; time.sleep(30)",
             "oom": ("import sys; sys.stderr.write('out of memory'); "
                     "sys.exit(1)"),
             "ok": "import json; print(json.dumps({'metric': 7.0}))"}
    r = SubprocessRunner(
        cmd_builder=lambda cfg: [sys.executable, "-c", flaky[cfg["kind"]]],
        timeout_s=3)
    rm = ResourceManager(r)
    rm.schedule_experiments(
        [Experiment(k, {"kind": k}) for k in ("hang", "oom", "ok")])
    rm.run()
    assert len(rm.finished_experiments) == 3
    errs = {e.name: e.error for e in rm.finished_experiments}
    assert "timeout" in errs["hang"] and "oom" in errs["oom"]
    assert rm.best().name == "ok" and rm.best().metric_val == 7.0


def test_autotune_headline_rehearsal_end_to_end(tmp_path):
    """The chip-drivable tool's whole loop on the CPU backend: guard ->
    subprocess experiments -> cost-model tuner -> AUTOTUNE_BEST.json.
    The tiny space's real lever is the micro-batch, so the tuned pick
    must not be the smallest batch."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "tools/autotune_headline.py", "--rehearse",
         "--trials", "6", "--early-stop", "6", "--timeout", "240",
         "--out-dir", str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [json.loads(l) for l in r.stdout.splitlines()
             if l.strip().startswith("{")]
    summary = lines[-1]
    assert summary["autotune"] == "done", summary
    assert summary["ran"] >= 3
    assert "best" in summary, summary
    art = json.load(open(tmp_path / "AUTOTUNE_BEST.json"))
    assert art["chosen_from"] == summary["best"]
    assert art["tokens_per_s"] == summary["tokens_per_s"]
    assert art["batch"] > 4, "tuner picked the smallest batch — " \
                             "cost-model ordering is not working"
    # per-experiment records persisted (ref parse_results analog)
    recs = os.listdir(tmp_path / "autotuning_results" / "headline")
    assert len([f for f in recs if f.endswith(".json")]) >= 3
