"""dslint phase 2 tests: the symbol table, the interprocedural rules
(DS011–DS014), the SARIF emitter, and the closure quick mode.

Same three-layer shape as tests/test_dslint.py:
  1. per-rule fixtures — for every interprocedural rule one
     true-positive package that MUST flag and one clean twin that MUST
     NOT (fixtures are in-memory parsed modules with package-style fake
     paths, so the path-scoped predicates see realistic trees);
  2. machinery — symbol-table collection (jit entries through
     ``functools.partial`` and bound-method registration, f-string
     expansion, fire forwarding), the import-graph closure, SARIF
     structure, CLI integration;
  3. self-scan — the repo's own tree must pass the FULL two-phase lint
     with an empty baseline (the PR's acceptance bar).
"""

import ast
import json
import subprocess
import sys

import pytest

from tools.dslint import (analyze_package, apply_baseline,
                          build_symbol_table, interproc_catalog,
                          interproc_rules, load_baseline, rule_catalog,
                          to_sarif)
from tools.dslint.core import REPO_ROOT, Finding, link_parents
from tools.dslint.interproc import (DonationFlowHazard, EnvFlagRegistry,
                                    FaultSiteIntegrity,
                                    TelemetrySchemaDrift)
from tools.dslint.symbols import closure_of


def table_of(files):
    """SymbolTable over ``{fake_path: source}`` — fixture packages."""
    parsed = []
    for path, src in files.items():
        tree = ast.parse(src)
        link_parents(tree)
        parsed.append((path, tree, src.splitlines()))
    return build_symbol_table(parsed)


def rule_hits(rule, files, **kw):
    return rule.check_package(table_of(files), **kw)


# ---------------------------------------------------------------------------
# DS011: donated-buffer use-after-dispatch across modules
# ---------------------------------------------------------------------------

ENGINE_MOD = (
    "import jax\n"
    "class Engine:\n"
    "    def __init__(self):\n"
    "        self._decode = jax.jit(self._decode_fn, donate_argnums=(0,))\n"
    "    def _decode_fn(self, cache, tok):\n"
    "        return cache\n")


def test_ds011_cross_module_read_after_donation():
    caller = (
        "class Serving:\n"
        "    def step(self, cache, tok):\n"
        "        out = self._decode(cache, tok)\n"
        "        return cache.sum() + out\n")
    hits = rule_hits(DonationFlowHazard(), {
        "deepspeed_tpu/inference/engine.py": ENGINE_MOD,
        "deepspeed_tpu/inference/serving.py": caller})
    assert len(hits) == 1
    assert hits[0].path == "deepspeed_tpu/inference/serving.py"
    assert "`cache` was donated to `_decode`" in hits[0].message
    # the finding names WHERE the entry was registered (cross-module)
    assert "deepspeed_tpu/inference/engine.py" in hits[0].message


def test_ds011_rebind_through_dispatch_is_clean():
    caller = (
        "class Serving:\n"
        "    def step(self, cache, tok):\n"
        "        cache = self._decode(cache, tok)\n"
        "        return cache\n")
    assert rule_hits(DonationFlowHazard(), {
        "deepspeed_tpu/inference/engine.py": ENGINE_MOD,
        "deepspeed_tpu/inference/serving.py": caller}) == []


def test_ds011_one_level_helper_inlining():
    # Cache.write forwards `pool` into the donated position — callers of
    # the HELPER get the same use-after check, one level deep
    helper_mod = (
        "import jax\n"
        "class Cache:\n"
        "    def __init__(self):\n"
        "        self._scatter = jax.jit(self._scatter_fn,\n"
        "                                donate_argnums=(0,))\n"
        "    def _scatter_fn(self, pool, blk):\n"
        "        return pool\n"
        "    def write(self, pool, blk):\n"
        "        return self._scatter(pool, blk)\n")
    bad_caller = (
        "class User:\n"
        "    def put(self, pool, blk):\n"
        "        r = self.write(pool, blk)\n"
        "        return pool[0] + r\n")
    hits = rule_hits(DonationFlowHazard(), {
        "deepspeed_tpu/inference/paged.py": helper_mod,
        "deepspeed_tpu/inference/user.py": bad_caller})
    assert len(hits) == 1
    assert "donates through a helper" in hits[0].message
    good_caller = (
        "class User:\n"
        "    def put(self, pool, blk):\n"
        "        pool = self.write(pool, blk)\n"
        "        return pool\n")
    assert rule_hits(DonationFlowHazard(), {
        "deepspeed_tpu/inference/paged.py": helper_mod,
        "deepspeed_tpu/inference/user.py": good_caller}) == []


# ---------------------------------------------------------------------------
# DS012: fault-site integrity
# ---------------------------------------------------------------------------

def test_ds012_fired_undeclared_and_declared_unfired(tmp_path):
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "ROBUSTNESS.md").write_text(
        "| `engine.step` | decode dispatch |\n")
    files = {
        "deepspeed_tpu/utils/faults.py":
            'KNOWN_SITES = {"engine.step", "cache.spill"}\n'
            "def fire(site):\n    pass\n",
        "deepspeed_tpu/inference/e.py":
            "def go(self):\n"
            '    self.faults.fire("engine.step")\n'
            '    self.faults.fire("ghost.site")\n'}
    msgs = [f.message for f in rule_hits(
        FaultSiteIntegrity(), files, docs_root=docs)]
    assert any("'ghost.site' is fired but not declared" in m for m in msgs)
    assert any("'cache.spill' is declared in KNOWN_SITES but never fired"
               in m for m in msgs)
    # cache.spill is also missing from the (tmp) robustness doc
    assert any("'cache.spill' is not documented" in m for m in msgs)
    assert not any("'engine.step'" in m for m in msgs)


def test_ds012_completeness_checks_off_in_partial_mode(tmp_path):
    files = {
        "deepspeed_tpu/utils/faults.py":
            'KNOWN_SITES = {"never.fired"}\n'}
    assert rule_hits(FaultSiteIntegrity(), files,
                     docs_root=tmp_path, partial=True) == []


FIRE_API = (
    "import jax\n"
    "class Api:\n"
    "    def __init__(self):\n"
    "        self._step = jax.jit(self._step_fn, donate_argnums=(0,))\n"
    "    def _step_fn(self, cache, tok):\n"
    "        return cache\n")


def test_ds012_public_entry_must_fire_before_donated_dispatch():
    bad = FIRE_API + (
        "    def decode(self, cache, tok):\n"
        "        cache = self._step(cache, tok)\n"
        "        return cache\n")
    hits = rule_hits(FaultSiteIntegrity(),
                     {"deepspeed_tpu/inference/api.py": bad}, partial=True)
    assert len(hits) == 1
    assert "public entry `decode` dispatches donated jit `_step`" \
        in hits[0].message
    good = FIRE_API + (
        "    def decode(self, cache, tok):\n"
        '        self.faults.maybe_fire("engine.step")\n'
        "        cache = self._step(cache, tok)\n"
        "        return cache\n")
    assert rule_hits(FaultSiteIntegrity(),
                     {"deepspeed_tpu/inference/api.py": good},
                     partial=True) == []


def test_ds012_fire_forwarding_is_transitive():
    # decode fires through TWO helper hops (_inject -> _fire -> faults);
    # the forwarder fixpoint must still count the literal as fired
    src = FIRE_API + (
        "    def _fire(self, site):\n"
        "        self.faults.maybe_fire(site)\n"
        "    def _inject(self, site):\n"
        "        self._fire(site)\n"
        "    def decode(self, cache, tok):\n"
        '        self._inject("engine.step")\n'
        "        cache = self._step(cache, tok)\n"
        "        return cache\n")
    assert rule_hits(FaultSiteIntegrity(),
                     {"deepspeed_tpu/inference/api.py": src},
                     partial=True) == []


def test_ds012_private_and_non_inference_paths_exempt():
    bad_body = (
        "    def _decode(self, cache, tok):\n"
        "        cache = self._step(cache, tok)\n"
        "        return cache\n")
    assert rule_hits(FaultSiteIntegrity(),
                     {"deepspeed_tpu/inference/api.py": FIRE_API + bad_body},
                     partial=True) == []
    public_outside = FIRE_API + (
        "    def decode(self, cache, tok):\n"
        "        cache = self._step(cache, tok)\n"
        "        return cache\n")
    assert rule_hits(FaultSiteIntegrity(),
                     {"deepspeed_tpu/runtime/api.py": public_outside},
                     partial=True) == []


# ---------------------------------------------------------------------------
# DS013: env-flag registry
# ---------------------------------------------------------------------------

ENV_MOD = ("FLAGS = dict([_mk('DS_A', 'bool', False, 'help')])\n")


def test_ds013_raw_read_under_package_flagged():
    reader = ("import os\n"
              "def pick():\n"
              "    return os.environ.get('DS_FOO', '0')\n")
    hits = rule_hits(EnvFlagRegistry(), {
        "deepspeed_tpu/utils/env.py": ENV_MOD,
        "deepspeed_tpu/runtime/zed.py": reader})
    assert len(hits) == 1
    assert "direct env read of 'DS_FOO'" in hits[0].message
    # identical read in tools/ (or the env layer itself) is exempt
    assert rule_hits(EnvFlagRegistry(), {
        "deepspeed_tpu/utils/env.py": ENV_MOD,
        "tools/bench.py": reader}) == []


def test_ds013_resolve_flag_must_name_declared_flag():
    user = ("from deepspeed_tpu.utils.env import resolve_flag\n"
            "def f():\n"
            "    return resolve_flag('DS_B')\n")
    hits = rule_hits(EnvFlagRegistry(), {
        "deepspeed_tpu/utils/env.py": ENV_MOD,
        "deepspeed_tpu/inference/s.py": user})
    assert len(hits) == 1
    assert "resolve_flag('DS_B') reads an undeclared flag" in hits[0].message
    ok = user.replace("DS_B", "DS_A")
    assert rule_hits(EnvFlagRegistry(), {
        "deepspeed_tpu/utils/env.py": ENV_MOD,
        "deepspeed_tpu/inference/s.py": ok}) == []


def test_ds013_bool_flag_defaulting_on_is_flagged():
    bad = "FLAGS = dict([_mk('DS_BAD', 'bool', True, 'help')])\n"
    hits = rule_hits(EnvFlagRegistry(),
                     {"deepspeed_tpu/utils/env.py": bad})
    assert len(hits) == 1
    assert "bool flag DS_BAD defaults ON" in hits[0].message
    # the default-check is a whole-tree completeness direction
    assert rule_hits(EnvFlagRegistry(),
                     {"deepspeed_tpu/utils/env.py": bad},
                     partial=True) == []


# ---------------------------------------------------------------------------
# DS014: telemetry schema drift
# ---------------------------------------------------------------------------

def _schema(tmp_path, metrics=(), events=(), patterns=()):
    p = tmp_path / "telemetry_schema.json"
    p.write_text(json.dumps({"version": 1, "metrics": list(metrics),
                             "events": list(events),
                             "metric_patterns": list(patterns)}))
    return p


def _docs(tmp_path, text):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    (d / "OBSERVABILITY.md").write_text(text)
    return d


REG_MOD = ("class T:\n"
           "    def __init__(self, metrics):\n"
           '        self.c = metrics.counter("svc_total")\n')


def test_ds014_code_schema_docs_in_agreement(tmp_path):
    schema = _schema(tmp_path, metrics=["svc_total"])
    docs = _docs(tmp_path, "| `svc_total` | counter | served requests |\n")
    assert rule_hits(TelemetrySchemaDrift(),
                     {"deepspeed_tpu/telemetry/x.py": REG_MOD},
                     docs_root=docs, schema_path=schema) == []


def test_ds014_drift_both_directions(tmp_path):
    schema = _schema(tmp_path, metrics=["svc_total", "stale_total"])
    docs = _docs(tmp_path, "| `svc_total` | counter | x |\n")
    extra = REG_MOD + (
        "    def more(self, metrics):\n"
        '        self.g = metrics.gauge("extra_depth")\n')
    msgs = [f.message for f in rule_hits(
        TelemetrySchemaDrift(), {"deepspeed_tpu/telemetry/x.py": extra},
        docs_root=docs, schema_path=schema)]
    assert any("'extra_depth' (gauge) is registered in code but missing"
               in m for m in msgs)
    assert any("'stale_total' is registered by no code path" in m
               for m in msgs)
    assert any("'stale_total' is in the schema but not mentioned" in m
               for m in msgs)


def test_ds014_brace_notation_documents_expanded_names(tmp_path):
    rule = TelemetrySchemaDrift()
    docs = _docs(tmp_path,
                 "| `svc_{a,b}_s` | histogram | phase split |\n"
                 "| `pool_r<i>` | gauge | per-replica |\n")
    known = {"svc_a_s", "svc_b_s", "pool_r0"}
    assert rule._check_docs(known, [], docs_root=docs) == []
    # a doc row naming a metric nothing registers is stale
    stale_docs = _docs(tmp_path, "| `gone_total` | counter | x |\n")
    out = rule._check_docs(set(), [], docs_root=stale_docs)
    assert len(out) == 1
    assert "names 'gone_total'" in out[0].message


def test_ds014_dynamic_fstring_needs_declared_pattern(tmp_path):
    dyn = ("class T:\n"
           "    def bind(self, metrics, i):\n"
           '        metrics.gauge(f"pool_health_r{i}")\n')
    schema = _schema(tmp_path)
    hits = rule_hits(TelemetrySchemaDrift(),
                     {"deepspeed_tpu/telemetry/d.py": dyn},
                     docs_root=_docs(tmp_path, ""), schema_path=schema)
    assert any("dynamic telemetry name pattern 'pool_health_r*'"
               in f.message for f in hits)
    ok_schema = _schema(tmp_path, patterns=["pool_health_r*"])
    assert rule_hits(TelemetrySchemaDrift(),
                     {"deepspeed_tpu/telemetry/d.py": dyn},
                     docs_root=_docs(tmp_path, "| `pool_health_r<i>` | g |\n"),
                     schema_path=ok_schema) == []


def test_ds014_test_registrations_are_not_contract(tmp_path):
    schema = _schema(tmp_path, metrics=[])
    assert rule_hits(TelemetrySchemaDrift(),
                     {"tests/test_telemetry.py": REG_MOD},
                     docs_root=_docs(tmp_path, ""),
                     schema_path=schema) == []


def test_ds014_checked_in_schema_matches_tree():
    # the real contract file parses and carries the three key families
    data = json.loads(
        (REPO_ROOT / "tools" / "dslint" /
         "telemetry_schema.json").read_text())
    assert data["metrics"] and data["events"]
    assert "serving_ttft" in data["metrics"]
    assert "spec_verify" in data["events"]
    # test-only fixture names must never enter the contract
    assert "requests_total" not in data["metrics"]


# ---------------------------------------------------------------------------
# symbol-table machinery
# ---------------------------------------------------------------------------

def test_symbols_partial_decorated_method_entry():
    src = ("from functools import partial\n"
           "import jax\n"
           "class M:\n"
           "    @partial(jax.jit, donate_argnums=(1,), static_argnums=(2,))\n"
           "    def step(self, cache, k):\n"
           "        return cache\n")
    t = table_of({"deepspeed_tpu/m.py": src})
    (e,) = t.jit_entries
    # `self` is dropped at call sites: decorator position 1 -> call pos 0
    assert e.key == ("attr", "step")
    assert e.donate == [0] and e.static == [1]


def test_symbols_bound_method_assign_entry():
    t = table_of({"deepspeed_tpu/m.py": ENGINE_MOD})
    (e,) = t.jit_entries
    assert e.key == ("attr", "_decode")
    assert e.donate == [0] and e.helper_of is None


def test_symbols_fstring_loop_expansion():
    src = ('PHASES = ("admission", "decode")\n'
           "class T:\n"
           "    def __init__(self, metrics):\n"
           "        for ph in PHASES:\n"
           '            metrics.histogram(f"step_{ph}_s")\n')
    t = table_of({"deepspeed_tpu/t.py": src})
    names = {r.name for r in t.metric_regs}
    assert names == {"step_admission_s", "step_decode_s"}
    assert all(not r.pattern for r in t.metric_regs)


def test_symbols_import_graph_and_closure():
    t = table_of({
        "deepspeed_tpu/a.py": "def f():\n    return 1\n",
        "deepspeed_tpu/b.py": "from deepspeed_tpu.a import f\n",
        "deepspeed_tpu/c.py": "import deepspeed_tpu.a\n",
        "deepspeed_tpu/d.py": "def g():\n    return 2\n"})
    assert t.imports["deepspeed_tpu/b.py"] == {"deepspeed_tpu/a.py"}
    assert t.imports["deepspeed_tpu/c.py"] == {"deepspeed_tpu/a.py"}
    assert t.imports["deepspeed_tpu/d.py"] == set()
    got = closure_of(["deepspeed_tpu/a.py"], t.imports)
    assert got == ["deepspeed_tpu/a.py", "deepspeed_tpu/b.py",
                   "deepspeed_tpu/c.py"]


# ---------------------------------------------------------------------------
# SARIF emitter
# ---------------------------------------------------------------------------

def test_sarif_structure_and_levels():
    new = Finding("DS001", "m.py", 3, 4, "sync in loop", "float(x)")
    old = Finding("DS011", "n.py", 1, 0, "donated read", "y + 1",
                  baselined=True)
    doc = to_sarif([new], [old])
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    # combined catalog: per-file AND interprocedural rules
    assert {"DS001", "DS011", "DS014"} <= set(ids)
    assert all(r["defaultConfiguration"]["level"] == "error"
               for r in run["tool"]["driver"]["rules"])
    r_new, r_old = run["results"]
    assert r_new["ruleId"] == "DS001" and r_new["level"] == "error"
    assert ids[r_new["ruleIndex"]] == "DS001"
    loc = r_new["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"] == {"uri": "m.py",
                                       "uriBaseId": "REPO_ROOT"}
    # SARIF columns are 1-based; finding cols are 0-based
    assert loc["region"]["startLine"] == 3
    assert loc["region"]["startColumn"] == 5
    assert loc["region"]["snippet"]["text"] == "float(x)"
    assert r_old["level"] == "note"
    assert run["originalUriBaseIds"]["REPO_ROOT"]["uri"].startswith("file://")


def test_sarif_line_zero_clamps_to_one():
    f = Finding("DS000", "m.py", 0, 0, "unreadable")
    loc = to_sarif([f])["runs"][0]["results"][0]["locations"][0]
    assert loc["physicalLocation"]["region"]["startLine"] == 1


# ---------------------------------------------------------------------------
# CLI: --sarif, --stats, --closure quick mode
# ---------------------------------------------------------------------------

def test_cli_full_run_writes_sarif_and_cache_then_closure_runs(tmp_path):
    sarif_path = tmp_path / "out.sarif"
    full = subprocess.run(
        [sys.executable, "-m", "tools.dslint", "deepspeed_tpu", "tools",
         "tests", "--sarif", str(sarif_path), "--stats"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert full.returncode == 0, full.stdout + full.stderr
    assert "total" in full.stderr          # --stats timing line
    log = json.loads(sarif_path.read_text())
    assert log["version"] == "2.1.0"
    assert log["runs"][0]["results"] == []     # tree lints clean
    # the full pass refreshed the import-graph cache quick mode needs
    cache = REPO_ROOT / "build" / "dslint_callgraph.json"
    assert cache.exists()
    quick = subprocess.run(
        [sys.executable, "-m", "tools.dslint", "--closure",
         "deepspeed_tpu/inference/serving.py"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert quick.returncode == 0, quick.stdout + quick.stderr
    assert "0 finding(s)" in quick.stdout


def test_cli_rules_filter_reaches_interproc():
    r = subprocess.run(
        [sys.executable, "-m", "tools.dslint", "--list-rules"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    for rid in ("DS011", "DS012", "DS013", "DS014",
                "DS015", "DS016", "DS017", "DS018"):
        assert rid in r.stdout


# ---------------------------------------------------------------------------
# self-scan: the full two-phase lint over the repo must stay clean
# ---------------------------------------------------------------------------

def test_two_phase_self_scan_zero_new_findings():
    stats = {}
    findings = analyze_package(
        [str(REPO_ROOT / "deepspeed_tpu"), str(REPO_ROOT / "tools"),
         str(REPO_ROOT / "tests")], stats=stats)
    new, _ = apply_baseline(findings, load_baseline())
    assert new == [], "non-baselined dslint findings:\n" + "\n".join(
        f.format() for f in new)
    # the acceptance budget scales with the tree (a fixed wall-clock
    # cap flakes as the repo grows and with machine load): 100ms of
    # CPU per scanned file keeps the lint interactive — the original
    # 10s cap at ~150 files, carried forward per-file
    assert stats["total_s"] < 0.1 * stats["files"], stats


def test_interproc_catalog_complete():
    cat = interproc_catalog()
    assert [r["id"] for r in cat] == ["DS011", "DS012", "DS013", "DS014",
                                      "DS015", "DS016", "DS017", "DS018"]
    assert all(r["rationale"] for r in cat)
    assert len(interproc_rules()) == len(cat)
    # combined catalogs don't collide
    all_ids = [r["id"] for r in rule_catalog()] + [r["id"] for r in cat]
    assert len(set(all_ids)) == len(all_ids)


# ---------------------------------------------------------------------------
# resolve_flag: the runtime half of the DS013 contract
# ---------------------------------------------------------------------------

def test_resolve_flag_bool_grammar():
    from deepspeed_tpu.utils.env import resolve_flag
    for word in ("on", "1", "true", "YES"):
        assert resolve_flag("DS_TELEMETRY", env={"DS_TELEMETRY": word}) \
            is True
    for word in ("", "off", "0", "false", "no"):
        assert resolve_flag("DS_TELEMETRY", env={"DS_TELEMETRY": word}) \
            is False
    assert resolve_flag("DS_TELEMETRY", env={}) is False
    with pytest.raises(ValueError, match="DS_TELEMETRY"):
        resolve_flag("DS_TELEMETRY", env={"DS_TELEMETRY": "maybe"})


def test_resolve_flag_choice_aliases_and_override():
    from deepspeed_tpu.utils.env import resolve_flag
    assert resolve_flag("DS_KV_QUANT", env={"DS_KV_QUANT": "on"}) == "int8"
    assert resolve_flag("DS_KV_QUANT", env={"DS_KV_QUANT": "no"}) == "off"
    assert resolve_flag("DS_KV_QUANT", override=True) == "int8"
    assert resolve_flag("DS_SPEC_K", env={"DS_SPEC_K": "7"}) == 7
    assert resolve_flag("DS_SPEC_K", override="9") == 9
    with pytest.raises(KeyError, match="undeclared"):
        resolve_flag("DS_NOT_A_FLAG")


def test_every_declared_bool_flag_defaults_off():
    # runtime mirror of the DS013 static check
    from deepspeed_tpu.utils.env import FLAGS
    for name, flag in FLAGS.items():
        if flag.kind == "bool":
            assert flag.default is False, name
