"""Tiered KV cache tests (tentpole: host-DRAM second tier for
refcount-zero cached prefix blocks in inference/host_tier.py /
inference/paged_cache.py, wired through the serving scheduler).

The contract under test (docs/KV_TIERING.md):

  1. ``DS_KV_HOST_TIER=off`` (the default) is BIT-IDENTICAL to the
     device-only cache — the off path stays the bit-reference;
  2. spilled-then-restored prefix blocks produce TOKEN-IDENTICAL
     streams to a cold re-prefill (the acceptance gate: the tier moves
     bytes, never changes tokens);
  3. every failure degrades, never corrupts: a dry free list, an
     injected ``cache.spill``/``cache.restore`` fault or a CRC
     mismatch (``cache.host_corrupt`` flips a REAL byte) ends in a
     cold-miss re-prefill or a plain eviction;
  4. the steady state compiles NOTHING — the fixed-width gather /
     scatter transfer programs are pre-warmed;
  5. interplay: int8 scale sidecars ride the spill, speculative decode
     and router drain keep their invariants with the tier active.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.host_tier import (HostBlockPool,
                                               HostCorruption,
                                               resolve_host_budget,
                                               resolve_host_tier)
from deepspeed_tpu.inference.paged_cache import (CacheExhausted,
                                                 PagedKVCache)
from deepspeed_tpu.inference.prefix_index import PrefixIndex
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=96, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def eng(devices):
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


def toks(*vals):
    return np.asarray(vals, np.int32)


def _arrays(seed=0, shape=(2, 4, 3)):
    r = np.random.default_rng(seed)
    return tuple(r.standard_normal(shape).astype(np.float32)
                 for _ in range(2))


# ---------------------------------------------------------------------------
# HostBlockPool unit tests (pure host)
# ---------------------------------------------------------------------------

def test_pool_put_get_roundtrip_and_budget_accounting():
    pool = HostBlockPool(budget_bytes=1 << 20)
    a = _arrays(0)
    k = pool.put(a)
    assert k is not None and len(pool) == 1
    assert pool.bytes_used == sum(x.nbytes for x in a)
    got = pool.get(k)
    for x, y in zip(a, got):
        np.testing.assert_array_equal(x, y)
    # the stored copy is independent of the caller's buffers
    a[0][...] = 0.0
    assert not np.array_equal(pool.get(k)[0], a[0])
    pool.discard(k)
    assert len(pool) == 0 and pool.bytes_used == 0
    # keys are monotone, never reused — a stale key can never alias a
    # fresh entry
    k2 = pool.put(_arrays(1))
    assert k2 != k
    with pytest.raises(KeyError):
        pool.get(k)


def test_pool_crc_detects_corruption():
    pool = HostBlockPool(budget_bytes=1 << 20)
    k = pool.put(_arrays(2))
    pool.corrupt(k)                       # flips a REAL stored byte
    with pytest.raises(HostCorruption) as e:
        pool.get(k)
    assert "0x" in str(e.value)           # names the stored checksum
    pool.discard(k)                       # poisoned entries still free
    assert pool.bytes_used == 0


def test_pool_budget_refusal_and_discard_idempotent():
    a = _arrays(3)
    pool = HostBlockPool(budget_bytes=sum(x.nbytes for x in a))
    k = pool.put(a)
    assert k is not None
    assert pool.put(_arrays(4)) is None   # over budget: refused, not oom
    pool.discard(k)
    pool.discard(k)                       # idempotent
    assert pool.put(_arrays(4)) is not None  # budget freed by discard


def test_host_tier_env_resolution(monkeypatch):
    monkeypatch.delenv("DS_KV_HOST_TIER", raising=False)
    assert resolve_host_tier(None) is False          # default off
    assert resolve_host_tier(True) is True
    monkeypatch.setenv("DS_KV_HOST_TIER", "on")
    assert resolve_host_tier(None) is True
    assert resolve_host_tier(False) is False         # arg wins over env
    monkeypatch.setenv("DS_KV_HOST_TIER", "banana")
    with pytest.raises(ValueError):
        resolve_host_tier(None)
    monkeypatch.setenv("DS_KV_HOST_BUDGET_MB", "2")
    assert resolve_host_budget(None) == 2 << 20


# ---------------------------------------------------------------------------
# PrefixIndex tier tags
# ---------------------------------------------------------------------------

def test_index_to_host_and_back_roundtrip():
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4, 5, 6), [7, 8, 9])
    ix.to_host(8, host_key=100)
    assert len(ix) == 2 and ix.host_len() == 1
    m = ix.match(toks(1, 2, 3, 4, 5, 6, 0), max_tokens=6)
    assert m.tiers == ["device", "host", "device"]
    assert m.block_ids == [7, 100, 9]     # host links carry HOST keys
    ix.to_device(100, 8)
    assert ix.host_len() == 0
    m = ix.match(toks(1, 2, 3, 4, 5, 6, 0), max_tokens=6)
    assert m.tiers == ["device"] * 3 and m.block_ids == [7, 8, 9]


def test_index_host_keys_never_collide_with_block_ids():
    """A host key NUMERICALLY equal to a live device block id must not
    alias it — host entries live in their own namespace."""
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4), [5, 6])
    ix.to_host(6, host_key=5)             # same number as device block 5
    assert 5 in ix                        # device node untouched
    m = ix.match(toks(1, 2, 3, 4, 0), max_tokens=4)
    assert m.tiers == ["device", "host"]
    assert m.block_ids == [5, 5]          # one device id, one host key


def test_index_cow_candidate_skips_host_links():
    """A partial tail block on HOST is not a COW candidate — the COW
    program addresses device pool bytes only; the match degrades to a
    plain (shorter) match instead."""
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4), [5, 6])
    m = ix.match(toks(1, 2, 3, 9, 9), max_tokens=5)
    assert m.cow_src == 6                 # device: mid-block COW offered
    ix.to_host(6, host_key=0)
    m = ix.match(toks(1, 2, 3, 9, 9), max_tokens=5)
    assert m.cow_src is None and m.matched == 2


def test_index_host_pinned_ancestors_not_evictable():
    """A device node with a HOST child can never leave leaf-first (the
    host child never leaves via device eviction), so evictable_count
    must not offer it — an overcount would let allocate start claiming
    and then die mid-allocation."""
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4, 5, 6), [7, 8, 9])
    ix.to_host(9, host_key=0)             # leaf to host: 7-8 both pinned
    assert ix.evictable_count(lambda b: True) == 0
    assert ix.pop_evictable(lambda b: True) is None
    ix.to_device(0, 9)                    # back on device: all 3 again
    assert ix.evictable_count(lambda b: True) == 3
    assert ix.pop_evictable(lambda b: True) == 9     # leaf-first order


def test_index_spill_candidates_lru_and_interior():
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4), [5, 6])
    ix.insert(toks(1, 2, 9, 9), [5, 8])
    ix.match(toks(1, 2, 9, 9, 0), max_tokens=4)      # 8 (and 5) recent
    cands = ix.spill_candidates(lambda b: True, limit=8)
    assert cands[0] == 6                  # stale branch goes first
    assert 5 in cands                     # INTERIOR nodes are offered
    assert ix.spill_candidates(lambda b: b == 8, limit=8) == [8]


def test_index_insert_over_host_node_upgrades_it():
    """Re-prefilling a chunk whose node sits on host (the degrade path
    re-computed it) upgrades the node to device and reports the
    displaced host key so the cache can discard the stale copy."""
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4), [5, 6])
    ix.to_host(6, host_key=42)
    dropped = []
    added = ix.insert(toks(1, 2, 3, 4), [5, 11], on_host_displaced=dropped.append)
    assert added == 1 and dropped == [42]
    assert ix.host_len() == 0 and 11 in ix
    m = ix.match(toks(1, 2, 3, 4, 0), max_tokens=4)
    assert m.block_ids == [5, 11] and m.tiers == ["device", "device"]


def test_index_remove_subtree_discards_descendants():
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4, 5, 6), [7, 8, 9])
    ix.insert(toks(1, 2, 3, 4, 7, 7), [7, 8, 10])
    ix.to_host(8, host_key=0)
    ix.to_host(10, host_key=1)
    dev, hosts = ix.remove_subtree(0)     # poisoned chunk at host key 0
    assert sorted(dev) == [9] and sorted(hosts) == [0, 1]
    assert len(ix) == 1 and ix.host_len() == 0       # only root child 7
    m = ix.match(toks(1, 2, 3, 4, 5, 6, 0), max_tokens=6)
    assert m.block_ids == [7] and m.matched == 2


# ---------------------------------------------------------------------------
# cache-level spill / restore mechanics
# ---------------------------------------------------------------------------

def cache_of(num_blocks=12, block_size=4, watermark=0, **kw):
    cfg, _ = tiny()
    kw.setdefault("prefix_cache", True)
    kw.setdefault("host_tier", True)
    kw.setdefault("spill_watermark", 99)  # constant pressure for tests
    kw.setdefault("transfer_blocks", 2)
    return PagedKVCache(cfg, num_slots=4, block_size=block_size,
                        num_blocks=num_blocks, dtype=jnp.float32,
                        watermark=watermark, **kw)


def prefilled(c, slot, tokens):
    m = c.allocate(slot, len(tokens), tokens=tokens)
    c.lengths[slot] = len(tokens)
    c.register_prefix(slot, tokens)
    return m


def _spill_all(c, ticks=6):
    for _ in range(ticks):
        c.spill_tick()


def test_cache_spill_restore_bit_roundtrip():
    """The headline mechanics: cached blocks spill to host (free list
    grows), a later matching admission restores them, and the restored
    pool bytes are BIT-IDENTICAL to what was spilled."""
    c = cache_of()
    t = np.arange(1, 13, dtype=np.int32)             # 3 blocks @ bs=4
    prefilled(c, 0, t)
    bids = list(c._owned[0][:2])
    # stamp recognizable bytes so the round-trip is a REAL bit check
    for j, b in enumerate(bids):
        c.k = c.k.at[:, b].set(float(j + 1))
        c.v = c.v.at[:, b].set(float(-(j + 1)))
    before = [(np.asarray(c.k[:, b]).copy(), np.asarray(c.v[:, b]).copy())
              for b in bids]
    c.free(0)
    free0 = len(c._free)
    _spill_all(c)
    assert c.host_spills >= 2 and c.host_blocks >= 2
    assert len(c._free) > free0           # spilled blocks were freed
    assert c.host_bytes == c.host_pool.bytes_used > 0
    # warm re-admission: the host links restore (free list has room)
    m = c.allocate(1, 12, tokens=t)
    assert m >= 8 and c.host_restores >= 2
    after_bids = c._owned[1][:2]
    for (k0, v0), b in zip(before, after_bids):
        np.testing.assert_array_equal(np.asarray(c.k[:, b]), k0)
        np.testing.assert_array_equal(np.asarray(c.v[:, b]), v0)
    assert len(c.drain_restore_ms()) >= 2            # latency samples
    assert c.drain_restore_ms() == []     # drained: swap-and-return


def test_cache_restore_is_free_list_only_and_truncates():
    """A dry free list TRUNCATES the match at the first host link — the
    restored prefix is kept, the tail re-prefills cold, and the host
    entry SURVIVES for a later retry."""
    c = cache_of(num_blocks=8)
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    _spill_all(c)
    assert c.host_blocks == 2
    # hold EVERY free block in one slot so restores cannot draw
    c.allocate(1, len(c._free) * c.block_size)
    assert len(c._free) == 0
    t12 = np.arange(1, 13, dtype=np.int32)
    with pytest.raises(CacheExhausted):
        # nothing free, nothing evictable -> the admission fails, but
        # the attempted restore must NOT have consumed the host copies
        c.allocate(2, 12, tokens=t12)
    assert c.host_blocks == 2 and c.host_restores == 0
    # release the hoarder: the SAME host entries now restore cleanly
    c.free(1)
    m = c.allocate(2, 12, tokens=t12)
    assert m >= 8 and c.host_restores == 2 and c.host_blocks == 0


def test_cache_in_transfer_blocks_are_not_reclaimable():
    """Mid-flight spill sources are excluded from EVERY reclaim path
    until the harvest settles them — eviction or release while the
    bytes fly would hand the block to two owners."""
    c = cache_of()
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    c.spill_tick()                        # dispatch only: nothing landed
    assert c._pending_spill is not None and len(c._in_transfer) == 2
    inflight = set(c._in_transfer)
    assert all(not c._reclaimable(b) for b in inflight)
    assert c.index.pop_evictable(c._reclaimable) is None
    assert all(b not in c._free for b in inflight)
    c.spill_tick()                        # harvest settles them
    assert not c._in_transfer and c.host_spills == 2


def test_cache_harvest_aborts_repinned_block():
    """A block re-claimed while its bytes were in flight must NOT land
    on host (the device copy stays authoritative) and must NOT be
    freed by the harvest."""
    c = cache_of()
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    c.spill_tick()                        # dispatch
    bid = next(iter(c._in_transfer))
    c._refcount[bid] += 1                 # simulate allocate pinning it
    c.spill_tick()                        # harvest
    assert c.host_spill_aborts >= 1
    assert bid in c.index and bid not in c._free
    c._refcount[bid] -= 1                 # settle the simulated pin


def test_cache_budget_exhaustion_degrades_to_plain_eviction():
    """A full host budget refuses the landing (budget_refusals counts
    it), the block stays device-cached, and ordinary LRU eviction still
    reclaims it — graceful degradation, not an error."""
    c = cache_of(host_budget_bytes=1)     # nothing fits
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    cached = c.cached_blocks
    _spill_all(c)
    assert c.host_budget_refusals >= 1 and c.host_spills == 0
    assert c.host_blocks == 0 and c.cached_blocks == cached
    assert c._spill_cooldown > 0          # backoff armed
    # plain eviction still works on those very blocks
    assert c.index.pop_evictable(c._reclaimable) is not None


def test_cache_spill_backoff_doubles_and_resets():
    c = cache_of(host_budget_bytes=1)
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    backoffs = []
    for _ in range(30):
        c.spill_tick()
        backoffs.append(c._spill_backoff)
    assert max(backoffs) >= 8             # kept doubling while refused
    assert c.host_spills == 0
    # lift the budget: the next landing resets the backoff to 1
    c.host_pool.budget_bytes = 64 << 20
    for _ in range(80):
        c.spill_tick()
        if c.host_spills:
            break
    assert c.host_spills >= 1 and c._spill_backoff == 1


def test_cache_corrupt_host_entry_discards_chain_and_reprefills():
    """A CRC mismatch on restore discards the poisoned subtree (every
    descendant's prefix runs through the bad bytes) and the admission
    degrades to a cold-miss re-prefill — never wrong tokens."""
    c = cache_of()
    t = np.arange(1, 13, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    _spill_all(c)
    assert c.host_blocks >= 2
    key = next(iter(c.host_pool._entries))
    c.host_pool.corrupt(key)
    m = c.allocate(1, 12, tokens=t)
    assert c.host_restore_failures >= 1
    assert key not in c.host_pool._entries           # poisoned: dropped
    # the truncated match is a VALID device prefix (possibly empty)
    assert m % c.block_size == 0
    # allocator stayed coherent: slot 1 holds exactly its blocks
    assert len(c._owned[1]) == c.blocks_for(12)


def test_cache_abort_transfers_settles_inflight():
    c = cache_of()
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    free0 = len(c._free)
    c.spill_tick()                        # dispatch: 2 blocks in flight
    assert len(c._in_transfer) == 2
    aborted = c.abort_transfers()
    assert aborted == 2
    assert not c._in_transfer and c._pending_spill is None
    assert c.host_spill_aborts >= 2 and c.host_blocks == 0
    assert len(c._free) == free0          # still cached, NOT freed
    assert (c._refcount >= 0).all()


def test_cache_abort_midstream_then_respill_stays_balanced():
    """Regression for the dslint DS016 resource-pairing audit of the
    host tier: an abort landing BETWEEN a harvest and the next dispatch
    must settle every `_in_transfer` entry exactly once (no orphaned
    entries, no double return to the free list), and the aborted blocks
    must remain spillable — a later pass picks them up cleanly."""
    c = cache_of()
    t = np.arange(1, 13, dtype=np.int32)             # 3 blocks @ bs=4
    prefilled(c, 0, t)
    c.free(0)
    free0 = len(c._free)
    c.spill_tick()                # dispatch batch 1 (2 blocks)
    c.spill_tick()                # harvest batch 1, dispatch batch 2
    assert c.host_spills == 2 and len(c._in_transfer) == 1
    aborted = c.abort_transfers()
    assert aborted == 1
    assert not c._in_transfer and c._pending_spill is None
    # the aborted block stayed cached + device-resident: it was NOT
    # returned to the free list (that would be a double release once a
    # later spill frees it again)
    assert len(c._free) == free0 + 2
    assert len(set(c._free)) == len(c._free)
    assert (c._refcount >= 0).all()
    # ...and the spill daemon picks it up again on the next pass
    _spill_all(c)
    assert c.host_spills == 3
    assert not c._in_transfer and c._pending_spill is None
    assert len(c._free) == free0 + 3
    assert len(set(c._free)) == len(c._free)


def test_cache_off_mode_is_inert():
    """host_tier=False keeps every new surface dormant: no pool, no
    transfers, spill_tick a no-op — the off path is the bit-reference
    by construction."""
    c = cache_of(host_tier=False)
    assert c.host_tier is False and c.host_pool is None
    t = np.arange(1, 9, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    assert c.spill_tick() == 0
    assert c.host_spills == 0 and c.host_blocks == 0
    st = c.stats()
    assert st["host_blocks"] == 0 and st["host_spills"] == 0
    # host tier REQUIRES the prefix index: without it the knob is inert
    c2 = cache_of(prefix_cache=False, host_tier=True)
    assert c2.host_tier is False


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

SYS_A = np.arange(1, 25, dtype=np.int32)
SYS_B = np.arange(60, 84, dtype=np.int32)


def fam_prompts(sys_prompt, n, seed, tail=4):
    r = np.random.default_rng(seed)
    return [np.concatenate([sys_prompt,
                            r.integers(30, 58, tail).astype(np.int32)])
            for _ in range(n)]


def tier_workload():
    """A-A-A B-B-B A-A: family A goes cold while B runs (its chain
    spills under pressure), then returns (its chain restores)."""
    return (fam_prompts(SYS_A, 3, 0) + fam_prompts(SYS_B, 3, 1)
            + fam_prompts(SYS_A, 2, 2))


def serve_tier(eng, prompts, host_tier=True, n_new=6, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 14)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("spill_watermark", 99)  # constant spill pressure
    srv = ServingEngine(eng, host_tier=host_tier, **kw)
    out = {}
    for i, p in enumerate(prompts):
        out.update(srv.run([ServeRequest(rid=i, prompt=p,
                                         max_new_tokens=n_new)]))
    return srv, out


def test_serving_restore_token_parity_vs_cold(eng):
    """THE acceptance gate: a serving run whose prefix hits restore
    from host DRAM is token-identical to solo cold re-prefills."""
    prompts = tier_workload()
    refs = _solo_refs(eng, prompts, 6)
    srv, out = serve_tier(eng, prompts)
    assert srv.cache.host_spills > 0, "the tier never spilled"
    touched = srv.cache.host_restores + srv.cache.host_restore_failures
    assert touched > 0, "no admission ever touched the host tier"
    if not faults_lib.active().faults:    # ambient chaos may eat them
        assert srv.cache.host_restores > 0, "no restore landed"
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            out[i], ref, err_msg=f"request {i} diverged after restore")
    assert srv.cache.held_blocks == 0
    assert (srv.cache._refcount == 0).all()


def test_serving_off_path_matches_on_path_streams(eng):
    """Tier on vs off over the same drive: identical streams (the tier
    changes where cold bytes live, never the tokens produced)."""
    prompts = tier_workload()
    s_on, out_on = serve_tier(eng, prompts, host_tier=True)
    s_off, out_off = serve_tier(eng, prompts, host_tier=False)
    assert s_on.host_tier and not s_off.host_tier
    assert s_off.cache.host_spills == 0
    for i in out_on:
        np.testing.assert_array_equal(out_on[i], out_off[i])


def test_serving_host_stats_mirrors_cache(eng):
    from deepspeed_tpu.telemetry import Telemetry
    srv, _ = serve_tier(eng, tier_workload(), telemetry=Telemetry())
    c = srv.cache
    assert srv.stats["host_spills"] == c.host_spills > 0
    assert srv.stats["host_restores"] == c.host_restores > 0
    assert srv.stats["host_blocks"] == c.host_blocks
    assert srv.stats["host_bytes"] == c.host_bytes
    assert srv.stats["host_restore_failures"] == c.host_restore_failures
    # telemetry: the restore-latency histogram saw every restore
    h = srv.metrics.histogram("kv_host_restore_ms")
    assert h.count == c.host_restores
    assert srv.metrics.gauge("kv_host_tier_bytes").value == c.host_bytes


def test_serving_env_knob_resolution(eng, monkeypatch):
    monkeypatch.setenv("DS_KV_HOST_TIER", "on")
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=14,
                        prefill_chunk=16, prefix_cache=True)
    assert srv.host_tier is True
    monkeypatch.setenv("DS_KV_HOST_TIER", "off")
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=14,
                        prefill_chunk=16, prefix_cache=True)
    assert srv.host_tier is False


def test_serving_compile_contract_with_host_tier(devices):
    """Compile-count contract, tier ON: after warmup (which pre-warms
    the fixed-width gather/scatter transfer programs) the steady state
    compiles NOTHING — spills and restores included."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    cfg, params = tiny()
    fresh = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = tier_workload()
    srv = ServingEngine(fresh, num_slots=2, block_size=8, num_blocks=14,
                        prefill_chunk=16, prefix_cache=True,
                        host_tier=True, spill_watermark=99)
    out = {}
    out.update(srv.run([ServeRequest(rid="w", prompt=prompts[0],
                                     max_new_tokens=4)]))
    watch = CompileWatch(max_compiles=0, label="host-tier steady state")
    with watch:
        for i, p in enumerate(prompts):
            srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)])
    assert srv.cache.host_spills > 0      # transfers ran INSIDE watch
    assert srv.cache.host_restores > 0


# ---------------------------------------------------------------------------
# chaos: the three new fault sites
# ---------------------------------------------------------------------------

def test_chaos_spill_fault_backs_off_blocks_stay_resident(eng):
    """An injected ``cache.spill`` exhaustion skips that batch: the
    candidates stay device-resident (nothing half-spilled), the daemon
    backs off, and a later retry lands — with full parity."""
    prompts = tier_workload()
    refs = _solo_refs(eng, prompts, 6)
    with faults_lib.injected(
            Fault("cache.spill", "cache_exhausted", step=0), seed=0) as inj:
        srv, out = serve_tier(eng, prompts)
    assert ("cache.spill", "cache_exhausted", 0) in inj.fired
    assert srv.cache.host_spills > 0      # the retry landed later
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert (srv.cache._refcount == 0).all()


def test_chaos_restore_fault_degrades_to_cold_miss(eng):
    """An injected ``cache.restore`` exhaustion truncates that match:
    the tail re-prefills cold, the host entry SURVIVES for a later
    retry, and parity holds."""
    prompts = tier_workload()
    refs = _solo_refs(eng, prompts, 6)
    with faults_lib.injected(
            Fault("cache.restore", "cache_exhausted", step=0),
            seed=0) as inj:
        srv, out = serve_tier(eng, prompts)
    assert ("cache.restore", "cache_exhausted", 0) in inj.fired
    assert srv.cache.host_restore_failures >= 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert srv.cache.held_blocks == 0


def test_chaos_host_corruption_crc_catches_and_reprefills(eng):
    """``cache.host_corrupt`` flips a REAL stored byte; the genuine
    CRC32 verify catches it, the poisoned chain is discarded, and the
    admission re-prefills — correct tokens, never garbage attention."""
    prompts = tier_workload()
    refs = _solo_refs(eng, prompts, 6)
    with faults_lib.injected(
            Fault("cache.host_corrupt", "cache_exhausted", step=0),
            seed=0) as inj:
        srv, out = serve_tier(eng, prompts)
    assert ("cache.host_corrupt", "cache_exhausted", 0) in inj.fired
    assert srv.cache.host_restore_failures >= 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert (srv.cache._refcount == 0).all()


# ---------------------------------------------------------------------------
# interplay: int8 pools, speculative decode, router drain
# ---------------------------------------------------------------------------

def test_hosttier_int8_scale_sidecars_roundtrip(eng):
    """Under DS_KV_QUANT=int8 a spilled block is 4 host arrays (int8
    K/V + fp32 scale sidecars) and the int8 tier-on streams are
    IDENTICAL to int8 tier-off (quantization noise is the fp-parity
    tolerance; the tier adds NONE on top)."""
    prompts = tier_workload()
    s_on, out_on = serve_tier(eng, prompts, host_tier=True,
                              kv_quant="int8")
    s_off, out_off = serve_tier(eng, prompts, host_tier=False,
                                kv_quant="int8")
    assert s_on.cache.host_spills > 0 and s_on.cache.host_restores > 0
    for arrays, _, _ in s_on.cache.host_pool._entries.values():
        assert len(arrays) == 4           # k, v, k_scale, v_scale
        assert arrays[0].dtype == np.int8
        assert arrays[2].dtype == np.float32
    for i in out_on:
        np.testing.assert_array_equal(out_on[i], out_off[i])


def test_hosttier_spec_decode_rollback_parity(eng):
    """Speculative decoding over host-restored prefix chains: rollback
    targets always sit above the prompt boundary, so restored shared
    blocks are never released by a reject — greedy parity holds."""
    prompts = tier_workload()
    s_on, out_on = serve_tier(eng, prompts, host_tier=True,
                              spec_decode=True, n_new=8)
    s_off, out_off = serve_tier(eng, prompts, host_tier=False,
                                spec_decode=True, n_new=8)
    assert s_on.cache.host_spills > 0
    for i in out_on:
        np.testing.assert_array_equal(out_on[i], out_off[i])
    assert (s_on.cache._refcount == 0).all()


def test_hosttier_router_drain_releases_restored_blocks(eng):
    """Retiring a replica mid-flight with transfers pending: the
    snapshot path aborts in-flight spills FIRST (no block is freed by
    a harvest after its slot released it), drained requests finish on
    the survivor, and the retired cache is fully released."""
    prompts = tier_workload()
    refs = _solo_refs(eng, prompts, 6)
    fleet = [ServingEngine(eng, num_slots=2, block_size=8, num_blocks=14,
                           prefill_chunk=16, prefix_cache=True,
                           host_tier=True, spill_watermark=99)
             for _ in range(2)]
    router = ReplicaRouter(fleet)
    for i, p in enumerate(prompts):
        router.submit(ServeRequest(rid=i, prompt=p, max_new_tokens=6))
    for _ in range(4):                    # let spills get in flight
        router.step()
    router.retire_replica(0)
    out = router.run()
    c0 = fleet[0].cache
    assert c0._pending_spill is None and not c0._in_transfer
    assert c0.held_blocks == 0 and (c0._refcount == 0).all()
    assert set(out) == set(range(len(prompts)))
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            out[i], ref, err_msg=f"request {i} lost parity over retire")
