"""Speculative decoding: greedy output must EXACTLY match the target
alone; a perfect draft accepts everything."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.speculative import generate_speculative
from deepspeed_tpu.models import gpt


def _engines(seed_t=0, seed_d=5):
    cfg_t = gpt.GPTConfig(vocab_size=128, n_layers=4, n_heads=4,
                          d_model=64, max_seq_len=64, dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
    cfg_d = gpt.GPTConfig(vocab_size=128, n_layers=1, n_heads=2,
                          d_model=32, max_seq_len=64, dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
    target = InferenceEngine(
        config=cfg_t, params=gpt.init_params(jax.random.PRNGKey(seed_t),
                                             cfg_t), dtype=jnp.float32)
    draft = InferenceEngine(
        config=cfg_d, params=gpt.init_params(jax.random.PRNGKey(seed_d),
                                             cfg_d), dtype=jnp.float32)
    return target, draft


def test_speculative_matches_target_greedy(devices):
    target, draft = _engines()
    toks = np.random.default_rng(0).integers(0, 128, (2, 7)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=12, temperature=0.0)
    for gamma in (1, 3, 5):
        got, stats = generate_speculative(target, draft, toks,
                                          max_new_tokens=12, gamma=gamma,
                                          return_stats=True)
        np.testing.assert_array_equal(got, ref,
                                      err_msg=f'gamma={gamma}')
        assert stats["tokens"] == 12


def test_speculative_perfect_draft_accepts_everything(devices):
    """Draft == target: every proposal must be accepted (gamma tokens
    per verify step), so the loop takes ~N/gamma rounds."""
    target, _ = _engines()
    toks = np.random.default_rng(1).integers(0, 128, (1, 5)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=12, temperature=0.0)
    got, stats = generate_speculative(target, target, toks,
                                      max_new_tokens=12, gamma=4,
                                      return_stats=True)
    np.testing.assert_array_equal(got, ref)
    # 12 tokens in 3 rounds (4+4+2 accepted; the tail round is short):
    # every proposal accepted, ~N/(gamma+1) target steps
    assert stats["accepted_per_round"] >= 3.3, stats
    assert stats["rounds"] <= 3, stats


def test_speculative_rejects_vocab_mismatch(devices):
    target, _ = _engines()
    cfg_bad = gpt.GPTConfig(vocab_size=96, n_layers=1, n_heads=2,
                            d_model=32, max_seq_len=64, dtype=jnp.float32,
                            use_flash_attention=False, remat=False)
    bad = InferenceEngine(config=cfg_bad,
                          params=gpt.init_params(jax.random.PRNGKey(2),
                                                 cfg_bad),
                          dtype=jnp.float32)
    with pytest.raises(AssertionError, match="vocabulary"):
        generate_speculative(target, bad, np.zeros((1, 4), np.int32))


def test_speculative_llama_dialect(devices):
    """Draft/target in the llama dialect (rotary + GQA + rmsnorm)."""
    cfg = gpt.preset("llama-tiny", dtype=jnp.float32,
                     use_flash_attention=False, remat=False)
    target = InferenceEngine(
        config=cfg, params=gpt.init_params(jax.random.PRNGKey(0), cfg),
        dtype=jnp.float32)
    draft = InferenceEngine(
        config=cfg, params=gpt.init_params(jax.random.PRNGKey(9), cfg),
        dtype=jnp.float32)
    toks = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (2, 6)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=10, temperature=0.0)
    got = generate_speculative(target, draft, toks, max_new_tokens=10,
                               gamma=3)
    np.testing.assert_array_equal(got, ref)
