"""Speculative decoding: greedy output must EXACTLY match the target
alone; a perfect draft accepts everything."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.speculative import generate_speculative
from deepspeed_tpu.models import gpt


def _engines(seed_t=0, seed_d=5):
    cfg_t = gpt.GPTConfig(vocab_size=128, n_layers=4, n_heads=4,
                          d_model=64, max_seq_len=64, dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
    cfg_d = gpt.GPTConfig(vocab_size=128, n_layers=1, n_heads=2,
                          d_model=32, max_seq_len=64, dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
    target = InferenceEngine(
        config=cfg_t, params=gpt.init_params(jax.random.PRNGKey(seed_t),
                                             cfg_t), dtype=jnp.float32)
    draft = InferenceEngine(
        config=cfg_d, params=gpt.init_params(jax.random.PRNGKey(seed_d),
                                             cfg_d), dtype=jnp.float32)
    return target, draft


def test_speculative_matches_target_greedy(devices):
    target, draft = _engines()
    toks = np.random.default_rng(0).integers(0, 128, (2, 7)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=12, temperature=0.0)
    for gamma in (1, 3, 5):
        got, stats = generate_speculative(target, draft, toks,
                                          max_new_tokens=12, gamma=gamma,
                                          return_stats=True)
        np.testing.assert_array_equal(got, ref,
                                      err_msg=f'gamma={gamma}')
        assert stats["tokens"] == 12


def test_speculative_perfect_draft_accepts_everything(devices):
    """Draft == target: every proposal must be accepted (gamma tokens
    per verify step), so the loop takes ~N/gamma rounds."""
    target, _ = _engines()
    toks = np.random.default_rng(1).integers(0, 128, (1, 5)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=12, temperature=0.0)
    got, stats = generate_speculative(target, target, toks,
                                      max_new_tokens=12, gamma=4,
                                      return_stats=True)
    np.testing.assert_array_equal(got, ref)
    # 12 tokens in 3 rounds (4+4+2 accepted; the tail round is short):
    # every proposal accepted, ~N/(gamma+1) target steps
    assert stats["accepted_per_round"] >= 3.3, stats
    assert stats["rounds"] <= 3, stats


def test_speculative_rejects_vocab_mismatch(devices):
    target, _ = _engines()
    cfg_bad = gpt.GPTConfig(vocab_size=96, n_layers=1, n_heads=2,
                            d_model=32, max_seq_len=64, dtype=jnp.float32,
                            use_flash_attention=False, remat=False)
    bad = InferenceEngine(config=cfg_bad,
                          params=gpt.init_params(jax.random.PRNGKey(2),
                                                 cfg_bad),
                          dtype=jnp.float32)
    with pytest.raises(AssertionError, match="vocabulary"):
        generate_speculative(target, bad, np.zeros((1, 4), np.int32))


def test_speculative_llama_dialect(devices):
    """Draft/target in the llama dialect (rotary + GQA + rmsnorm)."""
    cfg = gpt.preset("llama-tiny", dtype=jnp.float32,
                     use_flash_attention=False, remat=False)
    target = InferenceEngine(
        config=cfg, params=gpt.init_params(jax.random.PRNGKey(0), cfg),
        dtype=jnp.float32)
    draft = InferenceEngine(
        config=cfg, params=gpt.init_params(jax.random.PRNGKey(9), cfg),
        dtype=jnp.float32)
    toks = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (2, 6)).astype(np.int32)
    ref = target.generate(toks, max_new_tokens=10, temperature=0.0)
    got = generate_speculative(target, draft, toks, max_new_tokens=10,
                               gamma=3)
    np.testing.assert_array_equal(got, ref)


def test_sampled_path_tokens_pinned_across_refactor(devices):
    """Parity pin for the accept/resample dedup: moving the fp64
    Leviathan math into inference/sampling.py left the static sampled
    path bit-for-bit unchanged. The golden token ids below were
    captured from the pre-refactor implementation; any drift in the
    dist/accept/residual arithmetic shows up here as a token change."""
    target, draft = _engines()
    toks = np.random.default_rng(0).integers(0, 128, (2, 7)).astype(np.int32)
    goldens = {
        (0.9, 0, 7, 3): [[79, 67, 69, 100, 126, 117, 66, 31, 24, 111],
                         [114, 29, 127, 79, 27, 80, 63, 1, 87, 66]],
        (0.7, 8, 11, 4): [[9, 107, 107, 20, 92, 20, 20, 20, 97, 97],
                          [61, 57, 20, 4, 20, 81, 50, 74, 6, 85]],
    }
    for (temp, top_k, seed, gamma), want in goldens.items():
        got = generate_speculative(target, draft, toks, max_new_tokens=10,
                                   gamma=gamma, temperature=temp,
                                   top_k=top_k, seed=seed)
        np.testing.assert_array_equal(
            got[:, 7:], np.asarray(want, np.int32),
            err_msg=f"sampled static path drifted at temp={temp} "
                    f"top_k={top_k} seed={seed} gamma={gamma}")


def test_sampled_identical_engines_always_accept(devices):
    """p == q makes the acceptance probability exactly 1: sampled
    speculation with draft == target accepts every proposal."""
    target, _ = _engines()
    toks = np.random.default_rng(2).integers(0, 128, (1, 5)).astype(np.int32)
    got, stats = generate_speculative(target, target, toks,
                                      max_new_tokens=12, gamma=4,
                                      temperature=0.8, seed=11,
                                      return_stats=True)
    assert got.shape == (1, 17)
    assert ((got >= 0) & (got < 128)).all()
    # p and q come from DIFFERENT compiled programs (chunk verify vs
    # single-token decode); fp rounding can cost an occasional accept,
    # so allow one extra round over the ideal 3 (4+4+2)
    assert stats["rounds"] <= 4, stats
    assert stats["accepted_per_round"] >= 2.0, stats


@pytest.mark.parametrize("B,top_k", [(1, 0), (2, 0), (1, 6)])
def test_sampled_distribution_matches_target(devices, B, top_k):
    """Losslessness: the second generated token's empirical distribution
    matches the EXACT two-step target marginal sum_x1 p(x1) p(x2|x1),
    while the draft's own marginal is far away (negative control).
    B=2 adds a second row with a DIFFERENT prompt whose rejections force
    batch-lockstep cuts on row 0 — pinning the accepted-at-the-cut
    emission rule (a fresh p-sample there biases the marginal)."""
    cfg_t = gpt.GPTConfig(vocab_size=32, n_layers=2, n_heads=2,
                          d_model=32, max_seq_len=16, dtype=jnp.float32,
                          use_flash_attention=False, remat=False,
                          tie_embeddings=False)
    cfg_d = gpt.GPTConfig(vocab_size=32, n_layers=1, n_heads=2,
                          d_model=16, max_seq_len=16, dtype=jnp.float32,
                          use_flash_attention=False, remat=False,
                          tie_embeddings=False)

    def sharp_params(key, cfg):
        # random tiny nets emit ~uniform logits (no statistical power);
        # an amplified untied head gives each model a sharp, DISTINCT
        # distribution so bias would be visible
        prm = gpt.init_params(key, cfg)
        prm["lm_head"]["kernel"] = prm["lm_head"]["kernel"] * 12.0
        return prm

    target = InferenceEngine(config=cfg_t,
                             params=sharp_params(jax.random.PRNGKey(0),
                                                 cfg_t),
                             dtype=jnp.float32)
    draft = InferenceEngine(config=cfg_d,
                            params=sharp_params(jax.random.PRNGKey(4),
                                                cfg_d),
                            dtype=jnp.float32)
    V, temp = 32, 1.0
    prompt = np.array([[3, 7, 1]], np.int32)
    run_prompt = (prompt if B == 1
                  else np.array([[3, 7, 1], [5, 2, 9]], np.int32))

    def probs(logits):
        z = np.asarray(logits, np.float64) / temp
        if top_k > 0:
            kth = np.sort(z, axis=-1)[..., -top_k, None]
            z = np.where(z < kth, -np.inf, z)
        z = z - z.max(-1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(-1, keepdims=True)

    def marginal(eng):
        l1 = np.asarray(eng.forward(prompt))[0, -1]
        p1 = probs(l1)                                  # [V]
        batch = np.concatenate(
            [np.repeat(prompt, V, 0),
             np.arange(V, dtype=np.int32)[:, None]], axis=1)
        l2 = np.asarray(eng.forward(batch))[:, -1]      # [V, V]
        return p1 @ probs(l2)                           # [V]

    exact = marginal(target)
    control = marginal(draft)
    assert np.abs(exact - control).sum() / 2 > 0.15     # distinguishable

    N = 1200 if B == 1 else 900
    counts = np.zeros(V)
    for i in range(N):
        got = generate_speculative(target, draft, run_prompt,
                                   max_new_tokens=2, gamma=2,
                                   temperature=temp, top_k=top_k,
                                   seed=1000 + i)
        counts[got[0, -1]] += 1
    emp = counts / N
    tv = np.abs(emp - exact).sum() / 2
    tv_control = np.abs(emp - control).sum() / 2
    assert tv < (0.12 if B == 1 else 0.14), (tv, tv_control)
    assert tv < tv_control                              # closer to target
