"""Launcher, CLI, env-report tests (ref: tests/unit/test_runner.py-style
hostfile/filter parsing, no processes spawned except one end-to-end
single-host launch)."""

import json
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher import (
    decode_world_info, encode_world_info, fetch_hostfile,
    parse_inclusion_exclusion, parse_resource_filter)
from deepspeed_tpu.launcher.launch import build_child_env, resolve_node_rank
from deepspeed_tpu.launcher.runner import OpenMPIRunner, PDSHRunner, parse_args

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------- hostfile

def test_fetch_hostfile(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("worker-0 slots=4\nworker-1 slots=4\n\n# comment\n")
    pool = fetch_hostfile(str(hf))
    assert pool == {"worker-0": 4, "worker-1": 4}
    assert list(pool.keys()) == ["worker-0", "worker-1"]  # ordered


def test_fetch_hostfile_missing_returns_none(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_duplicate_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("w0 slots=2\nw0 slots=4\n")
    with pytest.raises(ValueError, match="already defined"):
        fetch_hostfile(str(hf))


def test_fetch_hostfile_bad_format_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("w0 slots\n")  # missing =N
    with pytest.raises(ValueError):
        fetch_hostfile(str(hf))


# ------------------------------------------------------------ filters

POOL = {"worker-0": 4, "worker-1": 4}


def test_include_whole_node():
    out = parse_inclusion_exclusion(POOL, "worker-0", "")
    assert out == {"worker-0": [0, 1, 2, 3]}


def test_include_slots():
    out = parse_inclusion_exclusion(POOL, "worker-0@worker-1:0,2", "")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 2]}


def test_exclude_slot():
    out = parse_inclusion_exclusion(POOL, "", "worker-1:0")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [1, 2, 3]}


def test_exclude_whole_node():
    out = parse_inclusion_exclusion(POOL, "", "worker-1")
    assert out == {"worker-0": [0, 1, 2, 3]}


def test_include_exclude_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        parse_resource_filter({"w": [0]}, include_str="w", exclude_str="w")


def test_unknown_host_raises():
    with pytest.raises(ValueError, match="not found"):
        parse_inclusion_exclusion(POOL, "worker-9", "")
    with pytest.raises(ValueError, match="No slot"):
        parse_inclusion_exclusion(POOL, "worker-0:9", "")


# --------------------------------------------------------- world info

def test_world_info_roundtrip():
    wi = {"worker-0": [0, 1], "worker-1": [2, 3]}
    assert decode_world_info(encode_world_info(wi)) == wi


def test_resolve_node_rank():
    wi = {"a": [0], "b": [0], "c": [0]}
    assert resolve_node_rank(wi, "b") == 1
    assert resolve_node_rank({"solo": [0]}, "") == 0
    with pytest.raises(RuntimeError):
        resolve_node_rank(wi, "zzz")


def test_build_child_env():
    env = build_child_env({}, "10.0.0.1", 29500, num_processes=4,
                          process_id=2, local_chips=[0, 1, 2, 3])
    assert env["DSTPU_COORDINATOR"] == "10.0.0.1:29500"
    assert env["DSTPU_NUM_PROCESSES"] == "4"
    assert env["DSTPU_PROCESS_ID"] == "2"
    assert env["RANK"] == "2" and env["WORLD_SIZE"] == "4"
    assert env["MASTER_ADDR"] == "10.0.0.1"


# ------------------------------------------------ multinode commands

def _args(extra=None):
    return parse_args(["--master_port", "29501"] + (extra or []) +
                      ["train.py", "--foo", "bar"])


def test_pdsh_cmd_shape():
    args = _args()
    r = PDSHRunner(args, encode_world_info({"w0": [0], "w1": [0]}))
    r.add_export("XLA_FLAGS", "--xla_dummy")
    cmd = r.get_cmd({}, {"w0": [0], "w1": [0]})
    joined = " ".join(cmd)
    assert cmd[0] == "pdsh"
    assert "-w w0,w1" in joined
    assert "deepspeed_tpu.launcher.launch" in joined
    assert "--master_port 29501" in joined
    assert "export XLA_FLAGS=--xla_dummy;" in joined
    assert "train.py --foo bar" in joined


def test_openmpi_cmd_shape():
    args = _args()
    r = OpenMPIRunner(args, encode_world_info({"w0": [0], "w1": [0]}))
    cmd = r.get_cmd({}, {"w0": [0], "w1": [0]})
    assert cmd[0] == "mpirun"
    assert "-n" in cmd and cmd[cmd.index("-n") + 1] == "2"
    assert "w0:1,w1:1" in cmd
    assert "train.py" in cmd


# ------------------------------------------------------- end to end

def test_single_host_launch_end_to_end(tmp_path):
    """runner → launch → child process with rendezvous env set
    (ref: stack 3.5 in SURVEY.md)."""
    script = tmp_path / "probe.py"
    out = tmp_path / "env.json"
    script.write_text(
        "import json, os\n"
        "keys = ['DSTPU_COORDINATOR', 'DSTPU_NUM_PROCESSES', "
        "'DSTPU_PROCESS_ID', 'RANK', 'WORLD_SIZE']\n"
        f"json.dump({{k: os.environ.get(k) for k in keys}}, "
        f"open({str(out)!r}, 'w'))\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", "/nonexistent", "--master_port", "29777",
         str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    probed = json.loads(out.read_text())
    assert probed["DSTPU_COORDINATOR"] == "127.0.0.1:29777"
    assert probed["RANK"] == "0" and probed["WORLD_SIZE"] == "1"


def test_env_report_runs(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.env_report"],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "environment report" in proc.stdout
    assert "devices:" in proc.stdout


def test_ds_elastic_cli(tmp_path):
    cfg = tmp_path / "ds.json"
    cfg.write_text(json.dumps({
        "elasticity": {"enabled": True, "max_train_batch_size": 2000,
                       "micro_batch_sizes": [2, 4, 6], "version": 0.1}}))
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.cli", "elastic",
         "-c", str(cfg), "-w", "4"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "1680" in proc.stdout
    assert "micro batch per chip" in proc.stdout


def test_ssh_runner_cmd_shape():
    """The plain-ssh transport (the reference's MVAPICH slot — see
    docs/PARITY.md row 60): one ssh per host, parallel, worst-rc join."""
    from deepspeed_tpu.launcher.runner import SSHRunner
    args = _args(["--launcher", "ssh"])
    r = SSHRunner(args, encode_world_info({"w0": [0], "w1": [0]}))
    r.add_export("XLA_FLAGS", "--xla_dummy")
    cmd = r.get_cmd({}, {"w0": [0], "w1": [0]})
    assert cmd[:2] == ["bash", "-c"]
    script = cmd[2]
    assert script.count("ssh -o StrictHostKeyChecking=no") == 2
    assert "--hostname w0" in script and "--hostname w1" in script
    assert "export XLA_FLAGS=--xla_dummy;" in script
    assert "wait $p || rc=$?" in script
    assert "train.py --foo bar" in script


def test_ds_ssh_fanout(tmp_path):
    """cli.py ssh (ref bin/ds_ssh): run a command on every hostfile
    node; per-host prefixes; worst exit code wins. Transport stubbed
    with a local script so no real ssh happens."""
    import stat
    import subprocess
    import sys as _sys

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("nodeA slots=4\nnodeB slots=4\n")
    stub = tmp_path / "fakessh"
    # args: host cmd... — 'fail' on nodeB to prove rc propagation
    stub.write_text("#!/bin/bash\nhost=$1; shift\n"
                    "echo \"$host ran: $*\"\n"
                    "[ \"$host\" = nodeB ] && exit 3\nexit 0\n")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)

    r = subprocess.run(
        [_sys.executable, "-m", "deepspeed_tpu.cli", "ssh",
         "-H", str(hostfile), "--ssh-cmd", str(stub), "--",
         "echo", "hi"],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 3, r.stdout + r.stderr
    assert "[nodeA] nodeA ran: echo hi" in r.stdout
    assert "[nodeB] nodeB ran: echo hi" in r.stdout
    assert "exit 3" in r.stderr
