"""Packed-sequence (segment ids) tests: packing N documents into one row
must reproduce the per-document forward/loss exactly.

TPU-first feature beyond the reference (v0.6.4 has no packing support);
kernel parity model per SURVEY §4 (fused op vs pure-jnp baseline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import gpt
from deepspeed_tpu.ops.attention import flash as F


def test_flash_segment_parity(devices, pallas_interpret):
    """Flash with segment_ids == jnp reference with the same mask."""
    B, S, H, D = 2, 256, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(
        np.repeat(np.arange(4), 64)[None].repeat(2, 0), jnp.int32)
    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, segment_ids=segs)
    ref = F.mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    # grads too
    def loss_f(q):
        return (F.flash_attention(q, k, v, causal=True, block_q=128,
                                  block_kv=128,
                                  segment_ids=segs) ** 2).sum()

    def loss_r(q):
        return (F.mha_reference(q, k, v, causal=True,
                                segment_ids=segs) ** 2).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_f)(q)), np.asarray(jax.grad(loss_r)(q)),
        rtol=5e-3, atol=5e-3)


def test_packed_equals_separate(devices):
    """Two documents packed into one row (segment_ids + restarted
    positions + boundary loss_mask) == the two documents run as separate
    rows."""
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=64, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(0)
    doc_a = r.integers(0, 96, 17).astype(np.int32)
    doc_b = r.integers(0, 96, 24).astype(np.int32)
    rng = jax.random.PRNGKey(1)

    # --- separate rows (lengths differ -> run one at a time) ----------
    def one(doc):
        batch = {"tokens": jnp.asarray(doc[None])}
        ll = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
        return float(ll) * (len(doc) - 1)   # total nll over the doc

    total_sep = one(doc_a) + one(doc_b)

    # --- packed row ---------------------------------------------------
    packed = np.concatenate([doc_a, doc_b])
    segs = np.concatenate([np.zeros(17, np.int32), np.ones(24, np.int32)])
    poss = np.concatenate([np.arange(17), np.arange(24)]).astype(np.int32)
    # next-token shift drops the last column; mask the boundary token
    # (doc_a's last token would predict doc_b's first)
    mask = np.ones(len(packed) - 1, np.float32)
    mask[16] = 0.0
    batch = {"tokens": jnp.asarray(packed[None]),
             "segment_ids": jnp.asarray(segs[None]),
             "positions": jnp.asarray(poss[None]),
             "loss_mask": jnp.asarray(mask[None])}
    packed_mean = float(gpt.loss_fn(params, batch, rng, cfg,
                                    deterministic=True))
    total_packed = packed_mean * mask.sum()

    np.testing.assert_allclose(total_packed, total_sep, rtol=1e-5)


def test_packed_chunked_ce_matches_dense(devices):
    import dataclasses
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=1, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(1)
    tokens = r.integers(0, 96, (2, 21)).astype(np.int32)
    segs = np.where(np.arange(21) < 10, 0, 1).astype(np.int32)[None].repeat(2, 0)
    poss = np.where(np.arange(21) < 10, np.arange(21),
                    np.arange(21) - 10).astype(np.int32)[None].repeat(2, 0)
    mask = np.ones((2, 20), np.float32)
    mask[:, 9] = 0.0
    batch = {"tokens": jnp.asarray(tokens),
             "segment_ids": jnp.asarray(segs),
             "positions": jnp.asarray(poss),
             "loss_mask": jnp.asarray(mask)}
    rng = jax.random.PRNGKey(2)
    dense = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
    chunked = gpt.loss_fn(params, batch, rng,
                          dataclasses.replace(cfg, loss_chunk=8),
                          deterministic=True)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_segment_ids_with_sp_raises(devices):
    cfg = gpt.GPTConfig(vocab_size=32, n_layers=1, n_heads=2, d_model=16,
                        max_seq_len=16, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        sequence_parallel=True)
    q = jnp.zeros((1, 8, 2, 8), jnp.float32)
    with pytest.raises(NotImplementedError):
        gpt._attention(q, q, q, cfg, segment_ids=jnp.zeros((1, 8), jnp.int32))
