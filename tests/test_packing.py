"""Packed-sequence (segment ids) tests: packing N documents into one row
must reproduce the per-document forward/loss exactly.

TPU-first feature beyond the reference (v0.6.4 has no packing support);
kernel parity model per SURVEY §4 (fused op vs pure-jnp baseline).
"""

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.models import gpt
from deepspeed_tpu.ops.attention import flash as F


def test_flash_segment_parity(devices, pallas_interpret):
    """Flash with segment_ids == jnp reference with the same mask."""
    B, S, H, D = 2, 256, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(
        np.repeat(np.arange(4), 64)[None].repeat(2, 0), jnp.int32)
    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, segment_ids=segs)
    ref = F.mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    # grads too
    def loss_f(q):
        return (F.flash_attention(q, k, v, causal=True, block_q=128,
                                  block_kv=128,
                                  segment_ids=segs) ** 2).sum()

    def loss_r(q):
        return (F.mha_reference(q, k, v, causal=True,
                                segment_ids=segs) ** 2).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_f)(q)), np.asarray(jax.grad(loss_r)(q)),
        rtol=5e-3, atol=5e-3)


def test_packed_equals_separate(devices):
    """Two documents packed into one row (segment_ids + restarted
    positions + boundary loss_mask) == the two documents run as separate
    rows."""
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=64, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(0)
    doc_a = r.integers(0, 96, 17).astype(np.int32)
    doc_b = r.integers(0, 96, 24).astype(np.int32)
    rng = jax.random.PRNGKey(1)

    # --- separate rows (lengths differ -> run one at a time) ----------
    def one(doc):
        batch = {"tokens": jnp.asarray(doc[None])}
        ll = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
        return float(ll) * (len(doc) - 1)   # total nll over the doc

    total_sep = one(doc_a) + one(doc_b)

    # --- packed row ---------------------------------------------------
    packed = np.concatenate([doc_a, doc_b])
    segs = np.concatenate([np.zeros(17, np.int32), np.ones(24, np.int32)])
    poss = np.concatenate([np.arange(17), np.arange(24)]).astype(np.int32)
    # next-token shift drops the last column; mask the boundary token
    # (doc_a's last token would predict doc_b's first)
    mask = np.ones(len(packed) - 1, np.float32)
    mask[16] = 0.0
    batch = {"tokens": jnp.asarray(packed[None]),
             "segment_ids": jnp.asarray(segs[None]),
             "positions": jnp.asarray(poss[None]),
             "loss_mask": jnp.asarray(mask[None])}
    packed_mean = float(gpt.loss_fn(params, batch, rng, cfg,
                                    deterministic=True))
    total_packed = packed_mean * mask.sum()

    np.testing.assert_allclose(total_packed, total_sep, rtol=1e-5)


def test_packed_chunked_ce_matches_dense(devices):
    import dataclasses
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=1, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(1)
    tokens = r.integers(0, 96, (2, 21)).astype(np.int32)
    segs = np.where(np.arange(21) < 10, 0, 1).astype(np.int32)[None].repeat(2, 0)
    poss = np.where(np.arange(21) < 10, np.arange(21),
                    np.arange(21) - 10).astype(np.int32)[None].repeat(2, 0)
    mask = np.ones((2, 20), np.float32)
    mask[:, 9] = 0.0
    batch = {"tokens": jnp.asarray(tokens),
             "segment_ids": jnp.asarray(segs),
             "positions": jnp.asarray(poss),
             "loss_mask": jnp.asarray(mask)}
    rng = jax.random.PRNGKey(2)
    dense = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
    chunked = gpt.loss_fn(params, batch, rng,
                          dataclasses.replace(cfg, loss_chunk=8),
                          deterministic=True)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_segment_ids_with_sp_matches_dense(devices):
    """Packing + ACTIVE sequence parallelism composes (the ring rotates
    per-token metadata with its K/V block): _attention under ring SP with
    segment_ids must match the dense local path exactly. With mesh=None
    SP is inert and packing keeps working through the local path."""
    import dataclasses
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(sequence=4, data=-1))
    cfg = gpt.GPTConfig(vocab_size=32, n_layers=1, n_heads=2, d_model=16,
                        max_seq_len=16, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        sequence_parallel=True, mesh=mesh)
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (1, 8, 2, 8), jnp.float32)
               for kk in ks)
    # uneven 3/5 split: the boundary falls INSIDE shard 1 (tokens 2-3),
    # so within-shard mixed-segment masking is exercised, not just the
    # rotated-block case
    segs = jnp.asarray(np.array([0, 0, 0, 1, 1, 1, 1, 1])[None], jnp.int32)
    out_sp = gpt._attention(q, k, v, cfg, segment_ids=segs)
    # inert SP (no mesh): packing works through the local path
    cfg0 = dataclasses.replace(cfg, mesh=None)
    out_local = gpt._attention(q, k, v, cfg0, segment_ids=segs)
    assert out_sp.shape == q.shape
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_local),
                               rtol=1e-5, atol=1e-6)


def test_pack_documents_roundtrip(devices):
    from deepspeed_tpu.runtime.dataloader import pack_documents
    r = np.random.default_rng(0)
    docs = [r.integers(1, 96, ln).astype(np.int32)
            for ln in (17, 24, 9, 40, 5)]
    packed = pack_documents(docs, seq_len=48)
    B, S = packed["tokens"].shape
    assert S == 48
    # every document's tokens appear contiguously under one segment id
    found = 0
    for doc in docs:
        ok = False
        for b in range(B):
            toks = packed["tokens"][b]
            for off in range(S - len(doc) + 1):
                if (toks[off:off + len(doc)] == doc).all() and \
                        len(set(packed["segment_ids"][b][off:off + len(doc)])) == 1 and \
                        packed["segment_ids"][b][off] >= 0:
                    ok = True
        found += ok
    assert found == len(docs)
    # loss_mask only covers within-document predictable positions
    assert packed["loss_mask"].sum() == sum(len(d) - 1 for d in docs)
    # and the packed batch trains through the GPT loss
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=1, n_heads=2, d_model=32,
                        max_seq_len=48, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    batch = {k: jnp.asarray(v) for k, v in packed.items()}
    loss = gpt.loss_fn(params, batch, jax.random.PRNGKey(1), cfg,
                       deterministic=True)
    assert np.isfinite(float(loss))


def test_packed_rotary_equals_separate(devices):
    """Packed rotary (GPT-J style) model: per-row positions restart the
    rotary phase per document — packed == separate."""
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=64, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        rotary_dim=8, use_wpe=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(3)
    doc_a = r.integers(0, 96, 11).astype(np.int32)
    doc_b = r.integers(0, 96, 14).astype(np.int32)
    rng = jax.random.PRNGKey(1)

    def one(doc):
        ll = gpt.loss_fn(params, {"tokens": jnp.asarray(doc[None])}, rng,
                         cfg, deterministic=True)
        return float(ll) * (len(doc) - 1)

    total_sep = one(doc_a) + one(doc_b)

    packed = np.concatenate([doc_a, doc_b])
    segs = np.concatenate([np.zeros(11, np.int32), np.ones(14, np.int32)])
    poss = np.concatenate([np.arange(11), np.arange(14)]).astype(np.int32)
    mask = np.ones(len(packed) - 1, np.float32)
    mask[10] = 0.0
    batch = {"tokens": jnp.asarray(packed[None]),
             "segment_ids": jnp.asarray(segs[None]),
             "positions": jnp.asarray(poss[None]),
             "loss_mask": jnp.asarray(mask[None])}
    packed_mean = float(gpt.loss_fn(params, batch, rng, cfg,
                                    deterministic=True))
    np.testing.assert_allclose(packed_mean * mask.sum(), total_sep,
                               rtol=1e-5)


def test_flash_mask_and_segments_combined(devices, pallas_interpret):
    """kv_mask and segment_ids together (packed rows that also carry
    padding): both mask operands thread through every kernel."""
    B, S, H, D = 1, 256, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(np.repeat([0, 1], 128)[None], jnp.int32)
    r = np.random.default_rng(4)
    kv_mask = jnp.asarray((r.random((B, S)) > 0.2).astype(np.float32))

    out = F.flash_attention(q, k, v, causal=True, block_q=128,
                            block_kv=128, kv_mask=kv_mask,
                            segment_ids=segs)
    ref = F.mha_reference(q, k, v, causal=True, kv_mask=kv_mask,
                          segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    row_w = kv_mask[..., None, None]

    def loss_f(q, k, v):
        o = F.flash_attention(q, k, v, causal=True, block_q=128,
                              block_kv=128, kv_mask=kv_mask,
                              segment_ids=segs)
        return ((o * row_w) ** 2).sum()

    def loss_r(q, k, v):
        o = F.mha_reference(q, k, v, causal=True, kv_mask=kv_mask,
                            segment_ids=segs)
        return ((o * row_w) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_packed_batch_through_engine(devices):
    """Packed batches (tokens + segment_ids + positions + loss_mask)
    shard over the data axes and train through the fused engine step."""
    import deepspeed_tpu
    from deepspeed_tpu.runtime.dataloader import pack_documents
    cfg = gpt.GPTConfig(vocab_size=96, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=33, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        loss_chunk=16)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "zero_optimization": {"stage": 3, "stage3_min_shard_size": 1},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
                "steps_per_print": 1000})
    r = np.random.default_rng(0)
    docs = [r.integers(1, 96, int(n)).astype(np.int32)
            for n in r.integers(8, 30, 24)]
    packed = pack_documents(docs, seq_len=33)
    assert packed["tokens"].shape[0] >= 8
    batch = {k: v[:8] for k, v in packed.items()}
    losses = [float(eng.train_batch(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# property-based packing invariants (hypothesis)
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:  # environment without hypothesis: collect the
    # rest of the module and skip just the property tests
    import pytest as _pytest

    def given(*a, **k):
        return _pytest.mark.skip(reason="hypothesis not installed")

    def settings(*a, **k):
        return lambda f: f

    class _NoStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _NoStrategies()


@settings(max_examples=40, deadline=None)
@given(st.lists(st.integers(min_value=2, max_value=40), min_size=1,
                max_size=12),
       st.integers(min_value=8, max_value=48))
def test_pack_documents_invariants(doc_lens, seq_len):
    """For ANY document mix: every token of every (>=2-token) document
    lands in exactly one row slot, segments never interleave, positions
    restart per document, and the loss mask is 1 exactly on within-doc
    next-token positions."""
    from deepspeed_tpu.runtime.dataloader import pack_documents

    r = np.random.default_rng(0)
    docs = [r.integers(1, 1000, ln).astype(np.int32) for ln in doc_lens]
    packed = pack_documents(docs, seq_len=seq_len, pad_token=0)
    toks, segs = packed["tokens"], packed["segment_ids"]
    poss, mask = packed["positions"], packed["loss_mask"]

    n, S = toks.shape
    assert segs.shape == (n, S) and poss.shape == (n, S)
    assert mask.shape == (n, S - 1)

    # total non-padding tokens == total tokens of all packed pieces
    # (docs longer than seq_len are split; trailing <2-token scraps drop)
    expected = 0
    for ln in doc_lens:
        while ln > seq_len:
            expected += seq_len
            ln -= seq_len
        if ln >= 2:
            expected += ln
    assert int((segs >= 0).sum()) == expected

    for i in range(n):
        row_segs = segs[i]
        # segments are contiguous runs starting at 0, padding (-1) only
        # at the tail
        valid = row_segs >= 0
        if valid.any():
            last_valid = np.max(np.nonzero(valid))
            assert valid[:last_valid + 1].all()   # no holes
            runs = row_segs[:last_valid + 1]
            # non-decreasing, increments of exactly 1
            d = np.diff(runs)
            assert ((d == 0) | (d == 1)).all()
        # positions restart at each segment start and increment inside
        for sid in np.unique(row_segs[row_segs >= 0]):
            where = np.nonzero(row_segs == sid)[0]
            np.testing.assert_array_equal(poss[i][where],
                                          np.arange(len(where)))
        # mask[i, j] == 1 iff token j and j+1 share a segment (>=0)
        same = (row_segs[:-1] == row_segs[1:]) & (row_segs[:-1] >= 0)
        np.testing.assert_array_equal(mask[i] > 0, same)
