"""ResNet/CIFAR workload tests — BASELINE.json config #1 analog
(ref: DeepSpeedExamples/cifar under ZeRO stage 1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import resnet


def tiny_cfg(**kw):
    d = dict(widths=(16, 32), depths=(1, 1), groups=4,
             dtype=jnp.float32, image_size=16)
    d.update(kw)
    return resnet.ResNetConfig(**d)


def synth_batch(n=16, size=16, seed=0):
    r = np.random.default_rng(seed)
    labels = r.integers(0, 10, n).astype(np.int32)
    means = np.random.default_rng(7).standard_normal(
        (10, 1, 1, 3)).astype(np.float32)
    images = means[labels] + 0.3 * r.standard_normal(
        (n, size, size, 3)).astype(np.float32)
    return {"images": images, "labels": labels}


def test_forward_shapes(devices):
    cfg = tiny_cfg()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    logits = resnet.forward(params, jnp.zeros((4, 16, 16, 3)), cfg)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_groupnorm_is_per_sample(devices):
    """The TPU-first BatchNorm replacement must not mix samples — the
    property that makes it dp-degree invariant (no SyncBN collective)."""
    cfg = tiny_cfg()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    b = synth_batch(8)
    full = resnet.forward(params, jnp.asarray(b["images"]), cfg)
    solo = resnet.forward(params, jnp.asarray(b["images"][:1]), cfg)
    np.testing.assert_allclose(np.asarray(full[:1]), np.asarray(solo),
                               rtol=1e-4, atol=1e-4)


def test_remat_matches(devices):
    cfg = tiny_cfg()
    cfg_r = tiny_cfg(remat=True)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    b = synth_batch(4)
    g0 = jax.grad(lambda p: resnet.loss_fn(
        p, {k: jnp.asarray(v) for k, v in b.items()}, None, cfg=cfg))(params)
    g1 = jax.grad(lambda p: resnet.loss_fn(
        p, {k: jnp.asarray(v) for k, v in b.items()}, None, cfg=cfg_r))(params)
    for a, c in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("stage", [1, 3])
def test_engine_trains_to_signal(devices, stage):
    """ZeRO-1 (the reference cifar config) and ZeRO-3: loss decreases and
    accuracy beats chance on separable synthetic data."""
    cfg = tiny_cfg()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    ds = {
        "train_batch_size": 16,
        "zero_optimization": {"stage": stage, "stage3_min_shard_size": 1},
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=resnet.make_loss_fn(cfg), model_parameters=params,
        config=ds)
    losses = []
    for i in range(25):
        losses.append(float(engine.train_batch(synth_batch(seed=i % 5))
                            ["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses
    acc = float(resnet.accuracy(engine.state.params, synth_batch(seed=99),
                                cfg))
    assert acc > 0.3, acc     # 10-class chance = 0.1


def test_checkpoint_roundtrip(devices, tmp_path):
    cfg = tiny_cfg()
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    ds = {"train_batch_size": 8,
          "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
          "steps_per_print": 1000}
    e1, _, _, _ = deepspeed_tpu.initialize(
        model=resnet.make_loss_fn(cfg), model_parameters=params, config=ds)
    e1.train_batch(synth_batch(8))
    e1.save_checkpoint(str(tmp_path))

    # fresh init: e1's donated train step consumed the first pytree
    params2 = resnet.init_params(jax.random.PRNGKey(0), cfg)
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=resnet.make_loss_fn(cfg), model_parameters=params2, config=ds)
    e2.load_checkpoint(str(tmp_path))
    b = synth_batch(8, seed=3)
    l1 = float(e1.train_batch(b)["loss"])
    l2 = float(e2.train_batch(b)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
