"""Inference-engine tests: decode==prefill parity, greedy generation,
TP inference, HF GPT-2 injection parity (ref: tests for
inference/engine.py + module_inject)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.models import gpt


def tiny():
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_matches_training_model(devices):
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(0).integers(0, 128, (2, 16)).astype(np.int32)
    out = eng.forward(tokens)
    ref = gpt.forward(params, jnp.asarray(tokens), cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_decode_matches_prefill(devices):
    """Token-by-token decode must reproduce full-sequence logits."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, (1, 8)).astype(np.int32)

    # greedy continuation via generate (prefill + decode path)
    gen = eng.generate(tokens, max_new_tokens=5, temperature=0.0)

    # reference: greedy argmax with full forward each step
    cur = tokens.copy()
    for _ in range(5):
        logits = np.asarray(gpt.forward(params, jnp.asarray(cur), cfg))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_generate_shapes_and_latency(devices):
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.zeros((2, 4), np.int32)
    out = eng.generate(tokens, max_new_tokens=6)
    assert out.shape == (2, 10)
    assert "prefill" in eng.latency_ms and "decode_per_token" in eng.latency_ms


def test_sampled_generation_valid_tokens(devices):
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=8,
                       temperature=1.0, top_k=5, seed=3)
    assert ((out >= 0) & (out < 128)).all()


def test_tp_inference_matches_single(devices):
    cfg, params = tiny()
    ref_eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(2).integers(0, 128, (1, 8)).astype(np.int32)
    ref = ref_eng.generate(tokens, max_new_tokens=4)

    tp_eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32,
                             mp_size=2)
    out = tp_eng.generate(tokens, max_new_tokens=4)
    np.testing.assert_array_equal(ref, out)
    qkv = tp_eng.params["block"]["qkv"]["kernel"]
    assert qkv.sharding.shard_shape(qkv.shape)[2] == qkv.shape[2] // 2


def test_init_inference_api(devices):
    cfg, params = tiny()
    eng = deepspeed_tpu.init_inference(model=(cfg, params), dtype=jnp.float32)
    assert isinstance(eng, InferenceEngine)


def test_hf_gpt2_injection(devices):
    """HF GPT-2 weights through the policy must reproduce HF logits."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    tokens = np.random.default_rng(0).integers(0, 96, (1, 8)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)


def test_hf_gpt_neo_injection(devices):
    """HF GPT-Neo (separate unbiased q/k/v, unscaled attention) through
    the policy must reproduce HF logits
    (ref: HFGPTNEOLayerPolicy, replace_policy.py:112)."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.GPTNeoConfig(
        vocab_size=96, max_position_embeddings=32, hidden_size=32,
        num_layers=2, num_heads=4, attention_types=[[["global"], 2]],
        resid_dropout=0.0, embed_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = transformers.GPTNeoForCausalLM(hf_cfg).eval()

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    assert eng.cfg.attn_scale == 1.0
    tokens = np.random.default_rng(0).integers(0, 96, (1, 8)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)


def test_hf_gptj_injection(devices):
    """HF GPT-J (rotary + parallel residual + untied biased head) through
    the policy must reproduce HF logits
    (ref: HFGPTJLayerPolicy, replace_policy.py:157)."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.GPTJConfig(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    assert eng.cfg.parallel_residual and eng.cfg.rotary_dim == 4
    tokens = np.random.default_rng(0).integers(0, 96, (1, 8)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)


def test_hf_gptj_generate(devices):
    """Rotary KV-cache decode matches full-forward greedy generation."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.GPTJConfig(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()
    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)

    tokens = np.random.default_rng(3).integers(0, 96, (1, 6)).astype(np.int32)
    gen = eng.generate(tokens, max_new_tokens=5, temperature=0.0)
    cur = tokens.copy()
    for _ in range(5):
        logits = np.asarray(eng.forward(cur))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_hf_bert_injection(devices):
    """HF BERT (post-LN encoder) through the policy must reproduce HF MLM
    logits (ref: HFBertLayerPolicy, replace_policy.py:49)."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.BertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=32, hidden_act="gelu_new",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf_model = transformers.BertForMaskedLM(hf_cfg).eval()

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    assert eng.is_encoder
    tokens = np.random.default_rng(0).integers(0, 96, (2, 8)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)
    with pytest.raises(NotImplementedError):
        eng.generate(tokens, max_new_tokens=2)


def test_moe_inference_decode(devices):
    """MoE-GPT KV-cache decode (GShard dispatch in eval mode) matches
    full-forward greedy generation
    (ref: ops/transformer/inference/moe_inference.py)."""
    from deepspeed_tpu.models import moe_gpt

    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=128, n_layers=2, n_heads=4, d_model=32, max_seq_len=64,
        use_flash_attention=False, remat=False, dtype=jnp.float32,
        num_experts=4, moe_k=1, capacity_factor=2.0, min_capacity=64)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)

    tokens = np.random.default_rng(5).integers(0, 128, (1, 6)).astype(np.int32)
    gen = eng.generate(tokens, max_new_tokens=4, temperature=0.0)
    cur = tokens.copy()
    for _ in range(4):
        logits = np.asarray(eng.forward(cur))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_hf_distilbert_injection(devices):
    """HF DistilBERT (separate q/k/v, post-LN, no token types) through
    the policy must reproduce HF hidden states
    (ref: HFDistilBertLayerPolicy in replace_policy.py)."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.DistilBertConfig(
        vocab_size=96, max_position_embeddings=32, dim=32, n_layers=2,
        n_heads=4, hidden_dim=64, dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = transformers.DistilBertModel(hf_cfg).eval()

    from deepspeed_tpu.inference.policy import resolve_model
    from deepspeed_tpu.models import bert
    cfg, params = resolve_model(hf_model)
    cfg.dtype = jnp.float32
    tokens = np.random.default_rng(0).integers(0, 96, (1, 8)).astype(np.int32)
    ours = np.asarray(bert.encode(params, jnp.asarray(tokens), cfg,
                                  deterministic=True))
    with torch.no_grad():
        theirs = hf_model(
            torch.tensor(tokens.astype(np.int64))).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)


def test_megatron_state_dict_injection(devices):
    """A Megatron-layout GPT state dict (q|k|v-contiguous fused
    projection) converts and produces logits parity with an equivalent
    native GPT (ref: MegatronLayerPolicy, replace_policy.py:202)."""
    from deepspeed_tpu.inference.policy import resolve_model
    from deepspeed_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=96, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=32, dtype=jnp.float32, remat=False,
                        use_flash_attention=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    # build the Megatron-style dict from the native params (torch [out,in])
    pre = "language_model.transformer.layers.{}."
    sd = {"language_model.embedding.word_embeddings.weight":
          np.asarray(params["wte"]["embedding"]),
          "language_model.embedding.position_embeddings.weight":
          np.asarray(params["wpe"]["embedding"]),
          "language_model.transformer.final_layernorm.weight":
          np.asarray(params["ln_f"]["scale"]),
          "language_model.transformer.final_layernorm.bias":
          np.asarray(params["ln_f"]["bias"]),
          "config": {"n_heads": 4}}
    blk = params["block"]
    names = {"input_layernorm": ("ln1", None),
             "attention.query_key_value": ("qkv", "kernel"),
             "attention.dense": ("attn_out", "kernel"),
             "post_attention_layernorm": ("ln2", None),
             "mlp.dense_h_to_4h": ("mlp_in", "kernel"),
             "mlp.dense_4h_to_h": ("mlp_out", "kernel")}
    for i in range(2):
        for mk, (ours_k, kind) in names.items():
            if kind is None:
                sd[pre.format(i) + mk + ".weight"] = \
                    np.asarray(blk[ours_k]["scale"][i])
                sd[pre.format(i) + mk + ".bias"] = \
                    np.asarray(blk[ours_k]["bias"][i])
            else:
                sd[pre.format(i) + mk + ".weight"] = \
                    np.asarray(blk[ours_k]["kernel"][i]).T
                sd[pre.format(i) + mk + ".bias"] = \
                    np.asarray(blk[ours_k]["bias"][i])

    mcfg, mparams = resolve_model(sd)
    assert mcfg.n_layers == 2 and mcfg.n_heads == 4 and mcfg.d_model == 32
    tokens = np.random.default_rng(1).integers(0, 96, (1, 8)).astype(np.int32)
    mcfg.dtype = jnp.float32
    ref = np.asarray(gpt.forward(params, jnp.asarray(tokens), cfg))
    out = np.asarray(gpt.forward(mparams, jnp.asarray(tokens), mcfg))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_generate_fused_matches_loop(devices):
    """The one-compiled-program decode scan reproduces the host-driven
    greedy loop token-for-token."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(3).integers(0, 128, (2, 9)).astype(np.int32)
    loop = eng.generate(tokens, max_new_tokens=7, temperature=0.0)
    fused = eng.generate_fused(tokens, max_new_tokens=7, temperature=0.0)
    np.testing.assert_array_equal(loop, fused)
    assert "decode_per_token_fused" in eng.latency_ms


def test_generate_fused_sampled_valid(devices):
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(4).integers(0, 128, (1, 5)).astype(np.int32)
    out = eng.generate_fused(tokens, max_new_tokens=6, temperature=0.8,
                             top_k=10, seed=7)
    assert out.shape == (1, 11)
    assert ((out >= 0) & (out < 128)).all()
    # same seed -> identical sampled sequence as the host-driven loop
    loop = eng.generate(tokens, max_new_tokens=6, temperature=0.8,
                        top_k=10, seed=7)
    np.testing.assert_array_equal(out, loop)


def test_tp_generate_fused_matches_single(devices):
    """Fused-scan generation under tensor-parallel inference reproduces
    the single-device greedy sequence."""
    cfg, params = tiny()
    ref_eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(9).integers(0, 128, (1, 8)).astype(np.int32)
    ref = ref_eng.generate_fused(tokens, max_new_tokens=5)

    tp_eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32,
                             mp_size=2)
    out = tp_eng.generate_fused(tokens, max_new_tokens=5)
    np.testing.assert_array_equal(ref, out)


def test_left_padded_generation_matches_unpadded(devices):
    """A left-padded variable-length batch generates exactly what each
    prompt generates alone (greedy), for both the host loop and the
    fused scan."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    r = np.random.default_rng(5)
    p1 = r.integers(1, 128, 5).astype(np.int32)
    p2 = r.integers(1, 128, 9).astype(np.int32)
    n = 6

    # reference: each prompt alone, no padding
    ref1 = eng.generate(p1[None], max_new_tokens=n)[0, len(p1):]
    ref2 = eng.generate(p2[None], max_new_tokens=n)[0, len(p2):]

    # left-padded batch
    S = 9
    tokens = np.zeros((2, S), np.int32)
    mask = np.zeros((2, S), np.float32)
    tokens[0, S - 5:] = p1
    mask[0, S - 5:] = 1
    tokens[1, :] = p2
    mask[1, :] = 1

    for fn in (eng.generate, eng.generate_fused):
        out = fn(tokens, max_new_tokens=n, attention_mask=mask)
        np.testing.assert_array_equal(out[0, S:], ref1)
        np.testing.assert_array_equal(out[1, S:], ref2)


def test_left_padded_rotary_matches_unpadded(devices):
    """Left-padded batches work for rotary (GPT-J style) models too —
    per-row rotary positions restart after the padding."""
    import dataclasses
    cfg, params = tiny()
    cfg = dataclasses.replace(cfg, rotary_dim=4, use_wpe=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    r = np.random.default_rng(6)
    p1 = r.integers(1, 128, 4).astype(np.int32)
    p2 = r.integers(1, 128, 7).astype(np.int32)
    n = 5
    ref1 = eng.generate(p1[None], max_new_tokens=n)[0, len(p1):]
    ref2 = eng.generate(p2[None], max_new_tokens=n)[0, len(p2):]

    S = 7
    tokens = np.zeros((2, S), np.int32)
    mask = np.zeros((2, S), np.float32)
    tokens[0, S - 4:] = p1
    mask[0, S - 4:] = 1
    tokens[1, :] = p2
    mask[1, :] = 1
    for fn in (eng.generate, eng.generate_fused):
        out = fn(tokens, max_new_tokens=n, attention_mask=mask)
        np.testing.assert_array_equal(out[0, S:], ref1)
        np.testing.assert_array_equal(out[1, S:], ref2)


def test_gqa_decode_matches_prefill(devices):
    """GQA model: token-by-token decode (grouped cache, half the kv
    heads) reproduces full-forward greedy generation; cache is smaller."""
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, n_kv_heads=2)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(8).integers(0, 128, (1, 8)).astype(np.int32)
    gen = eng.generate(tokens, max_new_tokens=5, temperature=0.0)
    cur = tokens.copy()
    for _ in range(5):
        logits = np.asarray(eng.forward(cur))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)
    # the cache really is grouped: kv-head dim = 2, not 4
    _, cache = eng._prefill(eng.params, jnp.asarray(tokens), None)
    assert cache["k"].shape[3] == 2
    # fused path agrees too
    fused = eng.generate_fused(tokens, max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(fused, gen)


def test_windowed_decode_matches_prefill(devices):
    """attn_window model: KV-cache decode masks the cache to the same
    sliding window the forward pass uses."""
    import dataclasses
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, attn_window=6)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    tokens = np.random.default_rng(12).integers(0, 128, (1, 10)).astype(np.int32)
    gen = eng.generate(tokens, max_new_tokens=8, temperature=0.0)
    cur = tokens.copy()
    for _ in range(8):
        logits = np.asarray(eng.forward(cur))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_mqa_and_composed_generation(devices):
    """n_kv_heads=1 (MQA) composed with attn_window and a left-padded
    batch: the full serving stack (grouped cache + windowed decode +
    per-row positions) reproduces the per-prompt solo runs."""
    import dataclasses
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, n_kv_heads=1, attn_window=6)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    r = np.random.default_rng(15)
    p1 = r.integers(1, 128, 5).astype(np.int32)
    p2 = r.integers(1, 128, 9).astype(np.int32)
    n = 6
    ref1 = eng.generate(p1[None], max_new_tokens=n)[0, len(p1):]
    ref2 = eng.generate(p2[None], max_new_tokens=n)[0, len(p2):]

    S = 9
    tokens = np.zeros((2, S), np.int32)
    mask = np.zeros((2, S), np.float32)
    tokens[0, S - 5:] = p1
    mask[0, S - 5:] = 1
    tokens[1] = p2
    mask[1] = 1
    for fn in (eng.generate, eng.generate_fused):
        out = fn(tokens, max_new_tokens=n, attention_mask=mask)
        np.testing.assert_array_equal(out[0, S:], ref1)
        np.testing.assert_array_equal(out[1, S:], ref2)
    # MQA cache: single kv head
    _, cache = eng._prefill(eng.params, jnp.asarray(tokens), None)
    assert cache["k"].shape[3] == 1


def test_int8_weight_only_quantization(devices):
    """dtype=jnp.int8 serves weight-only int8: kernels stored 1
    byte/param + per-channel scales, logits close to the fp32 engine,
    generation produces valid tokens (ref analog: init_inference
    dtype=torch.int8 kernel-inject quantization)."""
    cfg, params = tiny()
    ref = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    q = InferenceEngine(config=cfg, params=params, dtype=jnp.int8)
    toks = np.random.default_rng(0).integers(0, 128, (2, 8)).astype(np.int32)
    lo = np.asarray(ref.forward(toks))
    lq = np.asarray(q.forward(toks))
    # int8 per-channel weight error is small but nonzero
    assert np.max(np.abs(lo - lq)) < 0.15, np.max(np.abs(lo - lq))
    assert np.corrcoef(lo.ravel(), lq.ravel())[0, 1] > 0.999

    # the block kernels really are int8 in memory
    blk = q.params["block"]
    assert blk["qkv"]["q"].dtype == jnp.int8
    fp_bytes = sum(x.nbytes for x in jax.tree.leaves(ref.params["block"]))
    q_bytes = sum(x.nbytes for x in jax.tree.leaves(blk))
    assert q_bytes < 0.45 * fp_bytes, (q_bytes, fp_bytes)

    out = q.generate(toks, max_new_tokens=4, temperature=0.0)
    assert ((out >= 0) & (out < 128)).all()


def test_int8_llama_and_tp(devices):
    """int8 weight-only composes with the llama dialect (no-bias swiglu
    kernels, untied head) and with TP=2 (q shards like kernel; the
    per-channel scale replicates its size-1 row axis)."""
    from deepspeed_tpu.models import gpt as gptm
    cfg = gptm.preset("llama-tiny", dtype=jnp.float32,
                      use_flash_attention=False, remat=False)
    params = gptm.init_params(jax.random.PRNGKey(0), cfg)
    ref = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    toks = np.random.default_rng(1).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32)
    lo = np.asarray(ref.forward(toks))
    for mp in (1, 2):
        q = InferenceEngine(config=cfg, params=params, dtype=jnp.int8,
                            mp_size=mp)
        lq = np.asarray(q.forward(toks))
        assert np.corrcoef(lo.ravel(), lq.ravel())[0, 1] > 0.999, mp
        assert q.params["block"]["mlp_gate"]["q"].dtype == jnp.int8
