"""Per-request sampling subsystem tests (tentpole:
inference/sampling.py + the serving/spec wiring).

Layers:
  1. unit — SamplingParams validation, candidate-seed derivation, the
     fused ``sample_tokens`` greedy-lane bit-identity, the lax.top_k
     threshold's logits-equivalence with the old jnp.sort form, and the
     Philox position-uniform chain;
  2. serving — mixed greedy/sampled batches leave every temperature=0
     request bit-identical to plain greedy serving; same seed ->
     identical tokens across fresh engines, eviction/requeue and a
     router drain onto a survivor; distinct seeds diverge; stop
     sequences, logprobs and n>1 candidate expansion;
  3. contracts — the two-program steady state holds with zero
     recompiles across greedy<->sampled mixes (CompileWatch(0)), and
     the rejection-sampling spec verify is distribution-lossless
     (empirical marginal vs the exact fp64 target).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import sampling
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils.faults import Fault, FaultInjector

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_srv(eng, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return ServingEngine(eng, **defaults)


def run_solo(eng, prompt, max_new=8, srv_kw=None, **req_kw):
    srv = mk_srv(eng, **(srv_kw or {}))
    out = srv.run([ServeRequest(rid="r", prompt=prompt,
                                max_new_tokens=max_new, **req_kw)])
    return srv, out["r"]


# ---------------------------------------------------------------------------
# unit: params, seeds, fused sampler
# ---------------------------------------------------------------------------

def test_sampling_params_validation():
    sampling.SamplingParams().validate()           # greedy default is legal
    sampling.SamplingParams(temperature=0.7, top_k=40, top_p=0.9,
                            repetition_penalty=1.2).validate()
    for bad in (dict(temperature=-0.1), dict(top_k=-1), dict(top_p=0.0),
                dict(top_p=1.5), dict(repetition_penalty=0.0)):
        with pytest.raises(ValueError):
            sampling.SamplingParams(**bad).validate()
    # request fields win over engine defaults; None falls through
    req = ServeRequest(rid=0, prompt=np.zeros(1, np.int32),
                       temperature=0.5, seed=None)
    p = sampling.resolve_params(req, default_temperature=0.0,
                                default_seed=42)
    assert p.temperature == 0.5 and p.seed == 42 and p.sampled
    # malformed request knobs fail fast at resolve time
    req = ServeRequest(rid=0, prompt=np.zeros(1, np.int32), top_p=2.0)
    with pytest.raises(ValueError):
        sampling.resolve_params(req)


def test_candidate_seed_derivation():
    # candidate 0 IS the request seed (the original rid keeps its draw)
    assert sampling.candidate_seed(7, 0) == 7
    # derived seeds are mixed: adjacent seeds x adjacent indices stay
    # pairwise distinct (the naive seed+index scheme collides here)
    derived = {sampling.candidate_seed(s, i)
               for s in range(8) for i in range(4)}
    assert len(derived) == 8 * 4
    # deterministic: same (seed, index) -> same derived seed
    assert sampling.candidate_seed(7, 3) == sampling.candidate_seed(7, 3)


def test_sample_tokens_greedy_lane_bit_identity():
    """The core tentpole contract at unit level: in a mixed batch, the
    temperature=0 lanes return exactly argmax(logits) with softmax
    logprobs — the sampled lanes' machinery cannot perturb them — and
    an all-greedy batch returns the same thing."""
    rng = np.random.default_rng(3)
    B, V = 4, 128
    logits = jnp.asarray(rng.normal(size=(B, V)) * 3, jnp.float32)
    st = sampling.SlotSamplerState(B, V)
    st.admit(1, sampling.SamplingParams(temperature=0.8, top_k=20,
                                        top_p=0.9, seed=11,
                                        repetition_penalty=1.3),
             tokens=[5, 9])
    st.admit(3, sampling.SamplingParams(temperature=1.4, seed=12))
    keys, pos, temps, tks, tps, pens, seen = st.lanes([0, 4, 0, 2])
    toks, lps = sampling.sample_tokens(logits, jnp.asarray(keys), pos,
                                       temps, tks, tps, pens, seen)
    toks, lps = np.asarray(toks), np.asarray(lps)
    ref = np.argmax(np.asarray(logits), axis=-1)
    ref_lp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    assert toks[0] == ref[0] and toks[2] == ref[2]
    assert lps[0] == ref_lp[0, ref[0]] and lps[2] == ref_lp[2, ref[2]]
    # sampled lanes draw from the truncated distribution (still valid
    # token ids; logprob of the drawn token under the masked softmax)
    assert 0 <= toks[1] < V and 0 <= toks[3] < V
    assert np.all(lps <= 0.0)
    # all-greedy state: every lane is argmax, bitwise
    g = sampling.greedy_state(B, V)
    gt, glp = sampling.sample_tokens(logits, jnp.asarray(g[0]), *g[1:])
    np.testing.assert_array_equal(np.asarray(gt), ref)
    np.testing.assert_array_equal(np.asarray(glp),
                                  ref_lp[np.arange(B), ref])


def test_sample_tokens_seed_chain_reproducible():
    """Same (seed, position) -> same draw; the chain is a pure function
    of data, so replaying a position replays the token."""
    rng = np.random.default_rng(4)
    row = rng.normal(size=(1, 128)) * 2
    logits = jnp.asarray(np.tile(row, (2, 1)), jnp.float32)
    st = sampling.SlotSamplerState(2, 128)
    for slot, seed in ((0, 5), (1, 5)):
        st.admit(slot, sampling.SamplingParams(temperature=1.0, seed=seed))
    keys, pos, temps, tks, tps, pens, seen = st.lanes([3, 3])
    t1, _ = sampling.sample_tokens(logits, jnp.asarray(keys), pos, temps,
                                   tks, tps, pens, seen)
    t1 = np.asarray(t1)
    assert t1[0] == t1[1]        # same seed, same position, same logits
    # a different position advances the chain (draws are independent;
    # with 128 tokens at temperature 1 a collision across 4 positions
    # on BOTH slots at once is effectively impossible)
    draws = []
    for p in (4, 5, 6, 7):
        keys, pos, temps, tks, tps, pens, seen = st.lanes([p, p])
        t, _ = sampling.sample_tokens(logits, jnp.asarray(keys), pos,
                                      temps, tks, tps, pens, seen)
        draws.append(np.asarray(t))
    assert any(not np.array_equal(d, t1) for d in draws)


def test_topk_threshold_lax_topk_matches_sort():
    """Satellite 2's logits-equivalence pin: the ``jax.lax.top_k``
    k-th-largest threshold in ``engine._sample`` masks exactly the
    same logits as the historical full ``jnp.sort`` form, including
    k > vocab clamping and tied values at the boundary."""
    rng = np.random.default_rng(9)
    z = rng.normal(size=(3, 64)).astype(np.float32)
    z[0, :10] = z[0, 10]              # ties straddling the threshold
    zj = jnp.asarray(z)
    for k in (1, 4, 10, 63, 64, 500):
        k_eff = min(k, z.shape[-1])
        kth_sort = jnp.sort(zj, axis=-1)[:, -k_eff][:, None]
        kth_topk = jax.lax.top_k(zj, k_eff)[0][:, -1][:, None]
        np.testing.assert_array_equal(np.asarray(kth_sort),
                                      np.asarray(kth_topk))
        np.testing.assert_array_equal(
            np.asarray(jnp.where(zj < kth_sort, sampling.NEG_INF, zj)),
            np.asarray(jnp.where(zj < kth_topk, sampling.NEG_INF, zj)))


def test_engine_sample_topk_draws_from_truncated_support(eng):
    """engine._sample with top_k only ever emits tokens inside the
    true top-k set (the lax.top_k mask really truncates)."""
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(2, 1, 128)) * 2, jnp.float32)
    top = np.argsort(-np.asarray(logits)[:, -1], axis=-1)[:, :8]
    for s in range(20):
        toks = np.asarray(eng._sample(logits, jax.random.PRNGKey(s),
                                      temperature=1.0, top_k=8))
        for b in range(2):
            assert toks[b] in top[b]


def test_position_uniforms_counter_based():
    """The verify chain's uniforms are keyed by (seed, position) alone:
    no sequential state, so replaying a position after a drain/evict
    replays the identical decision — and chunk boundaries are
    invisible by construction."""
    a = sampling.position_uniforms(11, 4)
    np.testing.assert_array_equal(a, sampling.position_uniforms(11, 4))
    assert not np.array_equal(a, sampling.position_uniforms(11, 5))
    assert not np.array_equal(a, sampling.position_uniforms(12, 4))
    assert np.all((0.0 <= a) & (a < 1.0))


def test_spec_verify_marginal_is_lossless():
    """Statistical losslessness of the rejection-sampling verify
    (Leviathan/Chen): over many seeds, the marginal of the FIRST
    emitted token equals the target distribution p exactly — whether
    the deterministic draft proposed a likely or an unlikely token."""
    rng = np.random.default_rng(0)
    V, N = 8, 4000
    p = rng.dirichlet(np.ones(V), size=3)          # 3 verify rows
    for prop_tok in (int(np.argmax(p[0])), int(np.argmin(p[0]))):
        counts = np.zeros(V)
        for seed in range(N):
            toks, lps, acc = sampling.spec_verify_tokens(
                p, [prop_tok, 0], seed, pos0=0)
            counts[toks[0]] += 1
            # invariants: accepted prefix + exactly one extra token
            assert len(toks) == acc + 1 and len(lps) == len(toks)
        tv = 0.5 * np.abs(counts / N - p[0]).sum()
        assert tv < 0.03, f"first-token TV {tv} vs target (prop={prop_tok})"


def test_spec_verify_determinism_and_acceptance():
    """Same (seed, pos0) -> identical verify outcome; a proposal with
    p(x)=1 is always accepted; p(x)=0 is always rejected and the
    correction comes from the residual (x excluded)."""
    V = 6
    sure = np.zeros(V)
    sure[2] = 1.0
    rows = np.stack([sure, np.full(V, 1 / V)])
    toks, _, acc = sampling.spec_verify_tokens(rows, [2], 7, 0)
    assert acc == 1 and toks[0] == 2
    zero = np.full(V, 1 / (V - 1))
    zero[4] = 0.0
    rows = np.stack([zero, np.full(V, 1 / V)])
    for seed in range(50):
        toks, _, acc = sampling.spec_verify_tokens(rows, [4], seed, 0)
        assert acc == 0 and toks[0] != 4
    a = sampling.spec_verify_tokens(rows, [4], 3, 5)
    assert a == sampling.spec_verify_tokens(rows, [4], 3, 5)


# ---------------------------------------------------------------------------
# serving: greedy bit-identity, seeded reproducibility, knobs
# ---------------------------------------------------------------------------

def test_serving_mixed_batch_keeps_greedy_bit_identical(eng):
    """A greedy request decoded IN THE SAME BATCH as sampled requests
    produces exactly the plain-greedy serving/static output — the
    tentpole's acceptance bit-identity, at the scheduler level."""
    prompts = prompts_of((5, 9, 7), seed=21)
    ref = _solo_refs(eng, [prompts[0]], 8)[0]
    srv = mk_srv(eng, num_slots=3)
    out = srv.run([
        ServeRequest(rid="g", prompt=prompts[0], max_new_tokens=8),
        ServeRequest(rid="s1", prompt=prompts[1], max_new_tokens=8,
                     temperature=0.9, seed=3),
        ServeRequest(rid="s2", prompt=prompts[2], max_new_tokens=8,
                     temperature=1.3, top_k=16, top_p=0.95, seed=4),
    ])
    np.testing.assert_array_equal(out["g"], ref)
    assert srv.stats["peak_occupancy"] > 1       # they really cohabited
    assert srv.stats["sampled_tokens"] > 0
    # temperature=0 makes every other knob inert: same greedy bits even
    # with top_k/top_p/penalty/seed set
    _, out2 = run_solo(eng, prompts[0], max_new=8, temperature=0.0,
                       top_k=7, top_p=0.5, seed=99,
                       repetition_penalty=1.5)
    np.testing.assert_array_equal(out2, ref)


def test_serving_same_seed_reproducible_distinct_seeds_diverge(eng):
    p, = prompts_of((8,), seed=23)
    _, a = run_solo(eng, p, max_new=10, temperature=1.0, seed=17)
    _, b = run_solo(eng, p, max_new=10, temperature=1.0, seed=17)
    np.testing.assert_array_equal(a, b)          # bit-stable replay
    outs = [run_solo(eng, p, max_new=10, temperature=1.0, seed=s)[1]
            for s in (18, 19, 20)]
    assert any(not np.array_equal(a, o) for o in outs)


def test_serving_sampled_eviction_requeue_parity(eng):
    """The key-chain survives preemption: a sampled request evicted and
    requeued (recompute-on-resume) finishes with exactly the tokens an
    undisturbed roomy-pool run produces. The per-token key is a pure
    function of (seed, tokens generated), so the resumed chain continues
    where the evicted one stopped."""
    p1, p2 = prompts_of((10, 9), seed=9)
    kw = dict(temperature=0.9, top_k=32)
    _, ref1 = run_solo(eng, p1, max_new=12, seed=5, **kw)
    _, ref2 = run_solo(eng, p2, max_new=10, seed=6, **kw)
    srv = mk_srv(eng, num_blocks=7)              # tight pool: forces evict
    srv.cache.watermark = 0
    out = srv.run([
        ServeRequest(rid="a", prompt=p1, max_new_tokens=12, seed=5, **kw),
        ServeRequest(rid="b", prompt=p2, max_new_tokens=10, seed=6, **kw)])
    assert srv.stats["evictions"] >= 1
    np.testing.assert_array_equal(out["a"], ref1)
    np.testing.assert_array_equal(out["b"], ref2)


def test_router_drain_sampled_parity(eng):
    """A replica crash mid-decode drains sampled requests onto
    survivors token-identically: the snapshot carries the sampling
    params, and the key chain replays on the survivor."""
    prompts = prompts_of((5, 8, 11, 6), seed=29)
    refs = [run_solo(eng, p, max_new=8, temperature=0.8, top_p=0.9,
                     seed=40 + i)[1]
            for i, p in enumerate(prompts)]
    # horizon pinned: the step-7 crash is calibrated to one-token
    # steps (the N=8 drain-parity twin lives in test_horizon.py)
    inj = FaultInjector([Fault("router.step", "crash", step=7)], seed=0)
    fleet = [mk_srv(eng, faults=inj, decode_horizon=1) for _ in range(3)]
    router = ReplicaRouter(fleet, faults=inj)
    out = router.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8,
                                   temperature=0.8, top_p=0.9, seed=40 + i)
                      for i, p in enumerate(prompts)])
    assert inj.fired and router.stats["drained_requests"] >= 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            out[i], ref, err_msg=f"sampled request {i} lost drain parity")


def test_serving_stop_sequences(eng):
    """Generation finishes as soon as ``out`` ends with a stop
    sequence; the matched tokens stay in the output."""
    p, = prompts_of((6,), seed=31)
    _, ref = run_solo(eng, p, max_new=10)
    gen = [int(t) for t in ref[len(p):]]
    stop = gen[2:4]                      # a pair generate() really emits
    # expected cut: the FIRST generated position whose suffix matches
    # (repeated tokens can match before the pair's own position)
    cut = next(j + 1 for j in range(1, len(gen))
               if gen[j - 1:j + 1] == stop)
    srv, out = run_solo(eng, p, max_new=10, stop=[stop])
    np.testing.assert_array_equal(out, ref[:len(p) + cut])
    assert srv.stats["stop_hits"] == 1
    # a never-emitted stop sequence changes nothing
    srv2, out2 = run_solo(eng, p, max_new=10, stop=[[999999 % 128, 0, 0]])
    if not np.array_equal(out2, ref):           # only if it fired
        assert srv2.stats["stop_hits"] == 1
    else:
        assert srv2.stats["stop_hits"] == 0


def test_serving_logprobs_and_candidates(eng):
    """logprobs=True records one log-probability per emitted token;
    n>1 expands into independent candidates whose seeds derive from
    the request seed (candidate 0 IS the request)."""
    p, = prompts_of((7,), seed=33)
    srv = mk_srv(eng, num_slots=3)
    out = srv.run([ServeRequest(rid="c", prompt=p, max_new_tokens=6,
                                temperature=1.2, seed=50, n=3,
                                logprobs=True)])
    assert set(out) == {"c", "c#1", "c#2"}
    done = {r.rid: r for r in srv.finished}
    for rid in out:
        r = done[rid]
        assert len(r.out_logprobs) == len(r.out)
        assert all(lp <= 0.0 for lp in r.out_logprobs)
    # candidate 0 replays the plain n=1 run with the same seed
    _, solo = run_solo(eng, p, max_new=6, temperature=1.2, seed=50)
    np.testing.assert_array_equal(out["c"], solo)
    # high-temperature candidates diverge from one another
    assert (not np.array_equal(out["c"], out["c#1"])
            or not np.array_equal(out["c"], out["c#2"]))
    with pytest.raises(ValueError):
        mk_srv(eng).submit(ServeRequest(rid="bad", prompt=p, n=0))


def test_snapshot_roundtrip_carries_sampling_fields(eng):
    """pending_snapshot/from_snapshot round-trip the whole sampling
    surface — the params ARE the key-chain state (plus out), nothing
    device-side needs saving."""
    p, = prompts_of((6,), seed=35)
    req = ServeRequest(rid="s", prompt=p, max_new_tokens=9,
                       temperature=0.7, top_k=12, top_p=0.8, seed=77,
                       repetition_penalty=1.1, stop=[[3, 4]],
                       logprobs=True, n=1)
    # horizon pinned: "4 steps = prefill + a few decode tokens, still
    # mid-flight" assumes one token per step
    srv = mk_srv(eng, decode_horizon=1)
    srv.submit(req)
    for _ in range(4):                   # prefill + a few decode steps
        srv.step()
    snap = srv.pending_snapshot(release=True)
    assert len(snap) == 1
    back = ServeRequest.from_snapshot(snap[0])
    assert (back.temperature, back.top_k, back.top_p, back.seed,
            back.repetition_penalty) == (0.7, 12, 0.8, 77, 1.1)
    assert back.stop == [[3, 4]] and back.logprobs and back.n == 1
    assert back.out == req.out and back.out_logprobs == req.out_logprobs


# ---------------------------------------------------------------------------
# contracts: compile stability across greedy<->sampled mixes
# ---------------------------------------------------------------------------

def test_sampling_compile_contract_mixed_lanes(devices):
    """Sampling knobs are DATA: after one warmup, greedy-only, sampled-
    only and mixed workloads — including eviction/requeue — all run
    through the SAME two compiled programs with ZERO recompiles
    (CompileWatch(0)). This is the acceptance pin for 'params as
    slot-indexed arrays, not jit statics'."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)

    def workload(kw1, kw2):
        # horizon pinned: this test wraps the N=1 _decode_slots program
        # (the _decode_horizon family's contract is test_horizon.py's)
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                            prefill_chunk=8, spec_decode=False,
                            decode_horizon=1)
        srv.cache.watermark = 0          # tight pool: evict + requeue
        out = srv.run([
            ServeRequest(rid="a", prompt=p1, max_new_tokens=12, **kw1),
            ServeRequest(rid="b", prompt=p2, max_new_tokens=10, **kw2)])
        return srv, out

    sampled = dict(temperature=0.9, top_k=20, top_p=0.9, seed=3)
    srv, _ = workload(sampled, {})               # warmup: mixed batch
    assert srv.stats["evictions"] >= 1
    quant = srv.kv_quant == "int8"
    pf = eng._prefill_slot_q if quant else eng._prefill_slot
    dc = eng._decode_slots_q if quant else eng._decode_slots
    n_prefill, n_decode = cache_size(pf), cache_size(dc)
    if n_prefill is not None:
        assert (n_prefill, n_decode) == (1, 1), (
            f"sampled serving fragmented the steady state: "
            f"prefill={n_prefill} decode={n_decode} (expected 1+1)")

    watch = CompileWatch(max_compiles=0, label="sampled serving mixes")
    watch.wrap(pf)
    watch.wrap(dc)
    with watch:
        workload({}, {})                         # all greedy
        workload(sampled, sampled)               # all sampled
        workload({}, dict(temperature=1.4, repetition_penalty=1.2,
                          seed=8))               # mixed, new knob values
    if n_prefill is not None:
        assert cache_size(pf) == 1 and cache_size(dc) == 1


def test_spec_sampled_compile_contract(devices):
    """Spec-on twin: sampled requests keep the prefill=1 + verify=1 /
    decode=0 steady state with zero recompiles — the rejection verify
    is host math over logits the verify program already returns."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)

    def workload(kw1, kw2):
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, spec_decode=True, spec_k=3)
        out = srv.run([
            ServeRequest(rid="a", prompt=p1, max_new_tokens=10, **kw1),
            ServeRequest(rid="b", prompt=p2, max_new_tokens=10, **kw2)])
        return srv, out

    sampled = dict(temperature=0.8, seed=5)
    srv, _ = workload(sampled, {})               # warmup
    assert srv.stats["spec_steps"] > 0
    quant = srv.kv_quant == "int8"
    pf = eng._prefill_slot_q if quant else eng._prefill_slot
    vf = eng._verify_slots_q if quant else eng._verify_slots
    watch = CompileWatch(max_compiles=0, label="sampled spec serving")
    watch.wrap(pf)
    watch.wrap(vf)
    with watch:
        workload({}, sampled)
        workload(sampled, sampled)
    if cache_size(pf) is not None:
        assert cache_size(pf) == 1 and cache_size(vf) == 1


# ---------------------------------------------------------------------------
# spec-decode x sampling: end-to-end losslessness (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_spec_sampled_e2e_distribution_matches_plain(eng):
    """End-to-end statistical losslessness: with top_k=4 shrinking the
    support, the empirical distribution of the first DECODED token
    (the first spec-verified position) over many seeds matches between
    plain sampled serving and sampled spec-decode serving."""
    p, = prompts_of((6,), seed=41)
    kw = dict(temperature=1.0, top_k=4)
    N = 400
    freq = {False: {}, True: {}}
    for spec in (False, True):
        for s in range(N):
            srv_kw = (dict(spec_decode=True, spec_k=3) if spec
                      else dict(spec_decode=False))
            _, out = run_solo(eng, p, max_new=3, srv_kw=srv_kw,
                              seed=s, **kw)
            t = int(out[len(p) + 1])
            freq[spec][t] = freq[spec].get(t, 0) + 1
    support = set(freq[False]) | set(freq[True])
    # the second token mixes <=4-wide conditionals over the <=4
    # possible first tokens (which pair up by seed across the paths)
    assert len(support) <= 16           # truncation really bit
    tv = 0.5 * sum(abs(freq[False].get(t, 0) - freq[True].get(t, 0))
                   for t in support) / N
    assert tv < 0.16, f"spec vs plain sampled first-token TV {tv}"
