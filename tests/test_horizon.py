"""Fused multi-step decode tests (tentpole: ``DS_DECODE_HORIZON`` —
N decode iterations in ONE compiled ``lax.scan`` program per scheduler
step, docs/MULTISTEP.md).

The contract under test is bit-parity: a horizon only changes how many
host round-trips the same tokens take, never the tokens. Layers:

  1. knob — ``resolve_decode_horizon`` validation, env pickup, ctor
     override;
  2. parity — greedy AND sampled streams bit-equal to the N=1 serving
     run at N ∈ {2, 4, 8}, including mid-horizon stop hits (modeled and
     unmodeled), eviction/requeue on a tight pool, deadline timeouts
     (token-tick exact) and a router drain onto a survivor replica;
  3. composition — kv-quant / LoRA twins and the spec-decode precedence
     rule;
  4. contracts — zero steady-state recompiles (CompileWatch(0), one
     cached ``_decode_horizon`` entry per N) and the ``serving.horizon``
     chaos degrade to plain N=1 decode (never a wrong or missing
     token).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults
from deepspeed_tpu.utils.env import resolve_decode_horizon
from deepspeed_tpu.utils.faults import Fault, FaultInjector

pytestmark = pytest.mark.usefixtures("devices")

HORIZONS = (2, 4, 8)


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_srv(eng, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return ServingEngine(eng, **defaults)


def greedy_reqs(prompts, max_new=10):
    return [ServeRequest(rid=i, prompt=p, max_new_tokens=max_new)
            for i, p in enumerate(prompts)]


def sampled_reqs(prompts, max_new=10):
    """A mixed batch: two sampled lanes with different knob sets, one
    greedy lane, one repetition-penalized lane with logprobs."""
    a, b, c, d = prompts
    return [
        ServeRequest(rid="a", prompt=a, max_new_tokens=max_new,
                     temperature=0.9, top_k=32, seed=5),
        ServeRequest(rid="b", prompt=b, max_new_tokens=max_new),
        ServeRequest(rid="c", prompt=c, max_new_tokens=max_new,
                     temperature=0.7, top_p=0.9, seed=6),
        ServeRequest(rid="d", prompt=d, max_new_tokens=max_new,
                     temperature=0.8, repetition_penalty=1.2, seed=7,
                     logprobs=True),
    ]


# ---------------------------------------------------------------------------
# knob: validation, env pickup, ctor override
# ---------------------------------------------------------------------------

def test_resolve_decode_horizon_validation():
    assert resolve_decode_horizon(1) == 1
    assert resolve_decode_horizon(8) == 8
    assert resolve_decode_horizon(32) == 32          # the cap itself
    for bad in (0, -1, 33, 1000):
        with pytest.raises(ValueError, match="DS_DECODE_HORIZON"):
            resolve_decode_horizon(bad)


def test_horizon_env_flag_and_ctor_override(eng, monkeypatch):
    monkeypatch.setenv("DS_DECODE_HORIZON", "4")
    assert mk_srv(eng).decode_horizon == 4           # env pickup
    assert mk_srv(eng, decode_horizon=2).decode_horizon == 2  # ctor wins
    monkeypatch.setenv("DS_DECODE_HORIZON", "0")
    with pytest.raises(ValueError, match="DS_DECODE_HORIZON"):
        mk_srv(eng)
    monkeypatch.delenv("DS_DECODE_HORIZON")
    with pytest.raises(ValueError, match="DS_DECODE_HORIZON"):
        mk_srv(eng, decode_horizon=33)


# ---------------------------------------------------------------------------
# parity: greedy and sampled streams bit-equal to the N=1 run
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def greedy_ref(eng):
    """The N=1 serving run IS the bit-reference the horizon must hit."""
    prompts = prompts_of((5, 9, 12, 3))
    srv = mk_srv(eng, decode_horizon=1)
    out = srv.run(greedy_reqs(prompts))
    return prompts, out, srv.stats["decode_steps"]


# tier-1 runs ``-m 'not slow'`` under a hard wall-clock budget
# (ROADMAP.md); the heavier horizon workloads carry the slow mark and
# ride gate.sh, whose full and chaos legs run this file unfiltered.  A
# sub-second parity core (sampled parity, mid-horizon stops, deadline
# partials, drain, fault degrade, knob contracts) stays in tier-1.
@pytest.mark.slow
@pytest.mark.parametrize("n", HORIZONS)
def test_horizon_greedy_parity(eng, greedy_ref, n):
    prompts, ref, ref_steps = greedy_ref
    srv = mk_srv(eng, decode_horizon=n)
    out = srv.run(greedy_reqs(prompts))
    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            out[i], ref[i], err_msg=f"greedy request {i} diverged at N={n}")
    assert srv.stats["completed"] == len(prompts)
    # the gate's chaos leg reruns this test with ambient serving.horizon
    # faults injected — parity must hold regardless, but the
    # no-fallbacks claim only applies to a clean run
    if not faults.active().faults:
        assert srv.stats["horizon_fallbacks"] == 0
        # the fusion really happened: strictly fewer decode dispatches
        # than the one-token-per-step reference needed for the same
        # tokens
        assert srv.stats["decode_steps"] < ref_steps


@pytest.fixture(scope="module")
def sampled_ref(eng):
    prompts = prompts_of((6, 10, 8, 4), seed=17)
    srv = mk_srv(eng, decode_horizon=1)
    out = srv.run(sampled_reqs(prompts))
    lps = {r.rid: list(r.out_logprobs) for r in srv.finished}
    return prompts, out, lps


@pytest.mark.parametrize("n", HORIZONS)
def test_horizon_sampled_parity(eng, sampled_ref, n):
    """Mixed greedy/sampled batches stay bit-identical: the in-program
    sampler folds the same ``fold_in(seed, len(out) + i)`` key the N=1
    loop would at every emission."""
    prompts, ref, ref_lps = sampled_ref
    srv = mk_srv(eng, decode_horizon=n)
    out = srv.run(sampled_reqs(prompts))
    for rid in ("a", "b", "c", "d"):
        np.testing.assert_array_equal(
            out[rid], ref[rid],
            err_msg=f"sampled request {rid} diverged at N={n}")
    lps = {r.rid: list(r.out_logprobs) for r in srv.finished}
    np.testing.assert_allclose(lps["d"], ref_lps["d"], rtol=0, atol=1e-6)
    assert srv.stats["sampled_tokens"] > 0
    if not faults.active().faults:       # see test_horizon_greedy_parity
        assert srv.stats["horizon_fallbacks"] == 0


def test_horizon_mid_stop_parity(eng):
    """A stop sequence hit mid-horizon cuts the stream exactly where
    the N=1 loop would — both when the stop is MODELED in-program
    (lane freezes early) and when it is unmodeled surplus (the lane
    free-runs and the authoritative host check truncates)."""
    p, = prompts_of((6,), seed=31)
    srv1 = mk_srv(eng, decode_horizon=1)
    ref = srv1.run([ServeRequest(rid="r", prompt=p, max_new_tokens=10)])["r"]
    gen = [int(t) for t in ref[len(p):]]
    stop = gen[2:4]                      # a pair the run really emits
    cut = next(j + 1 for j in range(1, len(gen))
               if gen[j - 1:j + 1] == stop)
    expect = ref[:len(p) + cut]

    r1 = mk_srv(eng, decode_horizon=1).run(
        [ServeRequest(rid="r", prompt=p, max_new_tokens=10, stop=[stop])])
    np.testing.assert_array_equal(r1["r"], expect)

    # modeled: the single stop ships into the program
    srv8 = mk_srv(eng, decode_horizon=8)
    out = srv8.run([ServeRequest(rid="r", prompt=p, max_new_tokens=10,
                                 stop=[stop])])
    np.testing.assert_array_equal(out["r"], expect)
    assert srv8.stats["stop_hits"] == 1

    # unmodeled: the real stop rides 5th behind four decoys (the
    # program models at most 4) — the host check must still cut the
    # identical stream
    decoys = [[127, 126], [125, 124], [123, 122], [121, 120]]
    srv8u = mk_srv(eng, decode_horizon=8)
    outu = srv8u.run([ServeRequest(rid="r", prompt=p, max_new_tokens=10,
                                   stop=decoys + [stop])])
    np.testing.assert_array_equal(outu["r"], expect)
    assert srv8u.stats["stop_hits"] == 1


@pytest.mark.slow
def test_horizon_eviction_requeue_parity(eng):
    """A tight pool forces evict + requeue mid-run: the horizon's
    opportunistic capacity grants never change WHAT is evicted or the
    tokens the requeued request replays to."""
    p1, p2 = prompts_of((10, 9), seed=9)

    def run(n):
        srv = mk_srv(eng, num_blocks=7, decode_horizon=n)
        srv.cache.watermark = 0
        out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                       ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return srv, out

    srv1, ref = run(1)
    assert srv1.stats["evictions"] >= 1
    for n in HORIZONS:
        srv, out = run(n)
        assert srv.stats["evictions"] >= 1, f"N={n} workload lost its evict"
        for rid in ("a", "b"):
            np.testing.assert_array_equal(
                out[rid], ref[rid],
                err_msg=f"request {rid} diverged at N={n} under eviction")


def test_horizon_deadline_timeout_parity(eng):
    """Deadlines keep their token-count meaning: the in-horizon budget
    cap stamps no token past the deadline, so the partial output at
    timeout is IDENTICAL to the N=1 run's — same tokens, same count."""
    p1, p2 = prompts_of((6, 7), seed=5)

    def run(n):
        srv = mk_srv(eng, decode_horizon=n)
        out = srv.run([ServeRequest(rid="t", prompt=p1, max_new_tokens=30,
                                    deadline=4.0),
                       ServeRequest(rid="ok", prompt=p2, max_new_tokens=8)])
        done = {r.rid: r for r in srv.finished}
        return srv, out, done

    _, ref, refd = run(1)
    assert refd["t"].state == "timeout" and 0 < len(refd["t"].out) < 30
    for n in HORIZONS:
        srv, out, done = run(n)
        assert done["t"].state == "timeout", f"N={n}"
        np.testing.assert_array_equal(out["t"], ref["t"],
                                      err_msg=f"timeout partial at N={n}")
        np.testing.assert_array_equal(out["ok"], ref["ok"])
        assert srv.stats["timeouts"] == 1
        assert not srv.cache.active.any()


def test_horizon_router_drain_partial_parity(eng):
    """A replica crash mid-decode at N=8 drains requests onto survivors
    token-identically: the snapshot carries however far into its
    horizons the dead replica got (partial horizons are just shorter
    ``out`` lists), and the survivor replays the same streams."""
    prompts = prompts_of((5, 8, 11, 6), seed=29)
    refs = []
    for i, p in enumerate(prompts):
        srv = mk_srv(eng, decode_horizon=1)
        refs.append(srv.run([ServeRequest(
            rid=i, prompt=p, max_new_tokens=8, temperature=0.8,
            top_p=0.9, seed=40 + i)])[i])
    # crash early: at N=8 the whole run takes only a handful of router
    # steps (that IS the feature), so step=7 would never be visited
    inj = FaultInjector([Fault("router.step", "crash", step=2)], seed=0)
    fleet = [mk_srv(eng, decode_horizon=8, faults=inj) for _ in range(3)]
    router = ReplicaRouter(fleet, faults=inj)
    out = router.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8,
                                   temperature=0.8, top_p=0.9, seed=40 + i)
                      for i, p in enumerate(prompts)])
    assert inj.fired and router.stats["drained_requests"] >= 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            out[i], ref, err_msg=f"request {i} lost drain parity at N=8")


@pytest.mark.slow
def test_horizon_load_gen_stamps_exact(eng):
    """The load driver's latency records stay EXACT at N>1: tokens
    stamp at ``now + i * tick`` inside a horizon and the driver
    advances its clock by ``last_step_span``, so a no-queueing burst
    produces bit-identical per-request ttft/finished chains while the
    run takes strictly fewer scheduler steps. Prompts are capped to one
    prefill chunk: a slot still MID-PREFILL while others run a fused
    horizon only rejoins at the next horizon boundary — scheduling
    granularity the horizon coarsens by design (docs/MULTISTEP.md),
    not a stamp error."""
    from tools.load_gen import drive, make_requests
    entries = make_requests(seed=3, mix="chat", n=4, vocab_size=128,
                            max_prompt_len=8)

    def go(n):
        srv = mk_srv(eng, num_slots=4, num_blocks=64, decode_horizon=n)
        return drive(srv, entries, mode="closed", concurrency=4)

    r1, r8 = go(1), go(8)
    assert r8["steps"] < r1["steps"]     # the fusion really happened
    assert r1["per_request"] == r8["per_request"]
    for k in ("ttft_p50", "ttft_p95", "ttft_p99"):
        assert r1[k] == r8[k]


# ---------------------------------------------------------------------------
# composition: kv-quant / LoRA twins, spec precedence
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_horizon_kv_quant_parity(eng):
    """The int8 pool rides the ``_decode_horizon_q`` twin: the horizon
    must be bit-identical to the N=1 run ON THE SAME quantized layout
    (int8-vs-fp tolerance is test_kv_quant_serving's business)."""
    prompts = prompts_of((5, 9, 12, 3))
    ref = mk_srv(eng, kv_quant="int8", decode_horizon=1).run(
        greedy_reqs(prompts, max_new=8))
    srv = mk_srv(eng, kv_quant="int8", decode_horizon=8)
    out = srv.run(greedy_reqs(prompts, max_new=8))
    for i in range(len(prompts)):
        np.testing.assert_array_equal(out[i], ref[i])
    from deepspeed_tpu.utils.compile_guard import cache_size
    n_q = cache_size(eng._decode_horizon_q)
    if n_q is not None:                  # the quant twin really served
        assert n_q >= 1


@pytest.mark.slow
def test_horizon_lora_parity(eng):
    """Heterogeneous base+adapter batches decode through the
    ``_decode_horizon_l`` twin bit-identically to N=1."""
    from deepspeed_tpu.runtime.lora import add_lora, adapter_state_dict
    cfg, params = tiny()
    e = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    adapter = adapter_state_dict(
        add_lora(params, rng=jax.random.PRNGKey(1), rank=4, alpha=8.0))
    p1, p2 = prompts_of((7, 9), seed=11)

    def run(n):
        srv = mk_srv(e, decode_horizon=n, lora_serve=True,
                     lora_pool_blocks=2, lora_max_rank=4, lora_rank_block=4)
        srv.register_adapter("t1", adapter)
        return srv.run([
            ServeRequest(rid="ad", prompt=p1, max_new_tokens=8,
                         adapter_id="t1"),
            ServeRequest(rid="base", prompt=p2, max_new_tokens=8)])

    ref = run(1)
    out = run(8)
    for rid in ("ad", "base"):
        np.testing.assert_array_equal(out[rid], ref[rid])


@pytest.mark.slow
def test_horizon_spec_precedence(eng):
    """spec_decode already emits multiple tokens per dispatch, so it
    takes precedence: with both knobs on, the spec path runs (the knobs
    compose by configuration, not nested scans) and parity holds."""
    prompts = prompts_of((5, 9), seed=13)
    ref = mk_srv(eng, decode_horizon=1).run(greedy_reqs(prompts, max_new=8))
    srv = mk_srv(eng, spec_decode=True, decode_horizon=8)
    out = srv.run(greedy_reqs(prompts, max_new=8))
    for i in range(len(prompts)):
        np.testing.assert_array_equal(out[i], ref[i])
    assert srv.stats["spec_steps"] > 0   # the spec path really ran
    assert srv.decode_horizon == 8       # knob kept, just yielded to


# ---------------------------------------------------------------------------
# contracts: compile count, chaos degrade
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_horizon_steady_state_zero_recompiles(eng):
    """One compiled horizon program per N: after warmup a second full
    workload (admission churn, partial final horizons) compiles
    NOTHING, and the ``_decode_horizon`` cache holds one entry."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()                 # fresh engine: a clean jit cache
    e = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((10, 9, 6), seed=9)

    def run_workload():
        srv = mk_srv(e, decode_horizon=4)
        return srv, srv.run(greedy_reqs(prompts, max_new=9))

    _, warm = run_workload()
    pf, dh = e._prefill_slot, e._decode_horizon
    n_h = cache_size(dh)
    watch = CompileWatch(max_compiles=0, label="horizon steady state")
    watch.wrap(pf)
    watch.wrap(dh)
    with watch:                          # raises RecompileError on exit
        _, out = run_workload()          # if anything compiled
    for i in range(len(prompts)):
        np.testing.assert_array_equal(out[i], warm[i])
    if n_h is not None:
        assert cache_size(dh) == n_h == 1


def test_horizon_fault_degrades_to_single_step(eng):
    """An injected ``serving.horizon`` fault fires BEFORE any capacity
    or slot state moves and downgrades THAT step to plain N=1 decode
    (``horizon_fallbacks`` counts it); the run still drains with
    streams bit-identical to the clean N=1 run."""
    prompts = prompts_of((5, 9, 12, 3))
    ref = mk_srv(eng, decode_horizon=1).run(greedy_reqs(prompts))
    with faults.injected(Fault("serving.horizon", "device_error",
                               step=1, count=3)) as inj:
        srv = mk_srv(eng, decode_horizon=8)
        out = srv.run(greedy_reqs(prompts))
    assert inj.fired
    assert srv.stats["horizon_fallbacks"] >= 3
    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            out[i], ref[i], err_msg=f"request {i} diverged under degrade")
    assert srv.stats["completed"] == len(prompts)
