"""Runtime trace capture (TPU analog of NVTX instrumentation,
ref: deepspeed/utils/nvtx.py:4 + pytorch-profiler tutorial)."""

import os

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.utils import trace
from tests.simple_model import (random_batch, simple_model_loss,
                                simple_model_params)


def test_instrument_decorator_preserves_semantics():
    @trace.instrument("my_op")
    def f(x):
        return x * 2 + 1

    out = jax.jit(f)(np.arange(4.0))
    np.testing.assert_allclose(np.asarray(out), [1, 3, 5, 7])


def test_engine_trace_capture(tmp_path):
    params = simple_model_params(hidden_dim=16, nlayers=2, seed=0)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params,
        config={"train_batch_size": 8, "bf16": {"enabled": True},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000})
    batch = random_batch(8, 16, seed=0)
    engine.train_batch(batch)  # compile outside the trace window
    engine.start_trace(str(tmp_path), steps=2)
    engine.train_batch(batch)
    engine.train_batch(batch)
    # XPlane artifacts written
    found = []
    for root, _dirs, files in os.walk(str(tmp_path)):
        found += [f for f in files if f.endswith((".xplane.pb", ".json.gz",
                                                  ".trace.json.gz"))]
    assert found, "no trace artifacts written"
    # trace window closed — further steps run untraced
    engine.train_batch(batch)
