"""Chunked softmax cross-entropy: parity with the dense log_softmax path.

Model for these tests: the reference's kernel-vs-python parity style
(ref tests/unit/test_cuda_forward.py / test_cuda_backward.py — compare the
fused op against an unfused baseline within dtype tolerance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.cross_entropy import (chunked_softmax_xent,
                                             softmax_xent_ll)


def dense_ll(x, w, t, bias=None):
    logits = (x @ w.T).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]


@pytest.mark.parametrize("chunk", [7, 16, 64])
def test_ll_matches_dense(chunk):
    rng = np.random.default_rng(0)
    N, H, V = 48, 32, 97
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)), jnp.float32) * 0.1
    t = jnp.asarray(rng.integers(0, V, N), jnp.int32)
    got = softmax_xent_ll(x, w, t, chunk=chunk)
    want = dense_ll(x, w, t)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ll_bias_and_leading_shape():
    rng = np.random.default_rng(1)
    B, S, H, V = 2, 12, 16, 53
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)), jnp.float32) * 0.1
    b = jnp.asarray(rng.normal(size=(V,)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    got = softmax_xent_ll(x, w, t, bias=b, chunk=8)
    want = dense_ll(x, w, t, bias=b)
    assert got.shape == (B, S)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_grads_match_dense():
    rng = np.random.default_rng(2)
    N, H, V = 40, 24, 61
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)), jnp.float32) * 0.1
    b = jnp.asarray(rng.normal(size=(V,)), jnp.float32) * 0.1
    t = jnp.asarray(rng.integers(0, V, N), jnp.int32)

    def loss_chunked(x, w, b):
        return -softmax_xent_ll(x, w, t, bias=b, chunk=16).mean()

    def loss_dense(x, w, b):
        return -dense_ll(x, w, t, bias=b).mean()

    gc = jax.grad(loss_chunked, argnums=(0, 1, 2))(x, w, b)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(x, w, b)
    for a, c in zip(gc, gd):
        np.testing.assert_allclose(a, c, rtol=2e-4, atol=2e-5)


def test_masked_mean_loss():
    rng = np.random.default_rng(3)
    B, S, H, V = 2, 10, 16, 37
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)), jnp.float32) * 0.1
    t = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (B, S)), jnp.float32)
    got = chunked_softmax_xent(x, w, t, chunk=8, loss_mask=mask)
    ll = dense_ll(x, w, t)
    want = -(ll * mask).sum() / mask.sum()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_padding_rows_contribute_nothing():
    # N=13 with chunk=8 pads 3 rows; grads must equal the unpadded dense ones
    rng = np.random.default_rng(4)
    N, H, V = 13, 16, 29
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)), jnp.float32) * 0.1
    t = jnp.asarray(rng.integers(0, V, N), jnp.int32)
    gc = jax.grad(lambda w: -softmax_xent_ll(x, w, t, chunk=8).sum())(w)
    gd = jax.grad(lambda w: -dense_ll(x, w, t).sum())(w)
    np.testing.assert_allclose(gc, gd, rtol=2e-4, atol=2e-5)


def test_gpt_loss_chunked_parity():
    from deepspeed_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(5).integers(0, 128, (2, 17)), jnp.int32)}
    rng = jax.random.PRNGKey(1)
    dense = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
    import dataclasses
    cfg_c = dataclasses.replace(cfg, loss_chunk=8)
    chunked = gpt.loss_fn(params, batch, rng, cfg_c, deterministic=True)
    np.testing.assert_allclose(chunked, dense, rtol=1e-5, atol=1e-6)

    # and gradients agree end-to-end through the model
    gd = jax.grad(lambda p: gpt.loss_fn(p, batch, rng, cfg,
                                        deterministic=True))(params)
    gc = jax.grad(lambda p: gpt.loss_fn(p, batch, rng, cfg_c,
                                        deterministic=True))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=5e-4, atol=5e-5), gd, gc)


def test_untied_head_with_bias():
    from deepspeed_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=1, n_heads=2, d_model=16,
                        max_seq_len=16, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        tie_embeddings=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    params["lm_head"]["bias"] = jnp.asarray(
        np.random.default_rng(6).normal(size=(64,)), jnp.float32) * 0.1
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(7).integers(0, 64, (2, 9)), jnp.int32)}
    rng = jax.random.PRNGKey(1)
    import dataclasses
    dense = gpt.loss_fn(params, batch, rng, cfg, deterministic=True)
    chunked = gpt.loss_fn(params, batch, rng,
                          dataclasses.replace(cfg, loss_chunk=4),
                          deterministic=True)
    np.testing.assert_allclose(chunked, dense, rtol=1e-5, atol=1e-6)


def test_bert_mlm_loss_chunked_parity():
    import dataclasses
    from deepspeed_tpu.models import bert
    cfg = bert.BertConfig(vocab_size=96, n_layers=2, n_heads=2, d_model=32,
                          max_seq_len=32, dtype=jnp.float32, dropout=0.0)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(8)
    labels = r.integers(0, 96, (2, 16)).astype(np.int32)
    labels[r.random((2, 16)) > 0.2] = -1   # ~20% masked
    batch = {"tokens": jnp.asarray(r.integers(0, 96, (2, 16)), jnp.int32),
             "mlm_labels": jnp.asarray(labels),
             "nsp_labels": jnp.asarray(r.integers(0, 2, (2,)), jnp.int32)}
    rng = jax.random.PRNGKey(1)
    dense = bert.loss_fn(params, batch, rng, cfg, deterministic=True)
    chunked = bert.loss_fn(params, batch, rng,
                           dataclasses.replace(cfg, loss_chunk=8),
                           deterministic=True)
    np.testing.assert_allclose(chunked, dense, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# property-based chunked-CE invariants (hypothesis)
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:  # environment without hypothesis: collect the
    # rest of the module and skip just the property tests
    import pytest as _pytest

    def given(*a, **k):
        return _pytest.mark.skip(reason="hypothesis not installed")

    def settings(*a, **k):
        return lambda f: f

    class _NoStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _NoStrategies()


@settings(max_examples=25, deadline=None)
@given(st.integers(min_value=1, max_value=6),     # rows N
       st.integers(min_value=3, max_value=37),    # vocab V
       st.integers(min_value=1, max_value=9),     # chunk
       st.booleans(),                              # bias
       st.booleans())                              # mask
def test_chunked_matches_dense_any_shape(n, v, chunk, with_bias,
                                         with_mask):
    """For ANY (rows, vocab, chunk, bias, mask) combination — including
    chunk sizes that don't divide the row count — the fused chunked loss
    and its grads match the dense log-softmax computation."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent

    r = np.random.default_rng(n * 100 + v)
    h = 8
    x = jnp.asarray(r.standard_normal((n, h)), jnp.float32)
    w = jnp.asarray(r.standard_normal((v, h)), jnp.float32)
    b = jnp.asarray(r.standard_normal((v,)), jnp.float32) \
        if with_bias else None
    t = jnp.asarray(r.integers(0, v, (n,)), jnp.int32)
    m = jnp.asarray((r.random(n) > 0.3).astype(np.float32)) \
        if with_mask else None

    def dense(x, w):
        logits = x @ w.T + (b if b is not None else 0.0)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, t[:, None], 1).squeeze(-1)
        if m is not None:
            return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)
        return nll.mean()

    def fused(x, w):
        return chunked_softmax_xent(x[None], w, t[None], bias=b,
                                    chunk=chunk,
                                    loss_mask=None if m is None
                                    else m[None])

    np.testing.assert_allclose(float(dense(x, w)), float(fused(x, w)),
                               rtol=1e-5, atol=1e-6)
    gd = jax.grad(dense, argnums=(0, 1))(x, w)
    gf = jax.grad(fused, argnums=(0, 1))(x, w)
    for a, c in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)
