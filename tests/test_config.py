"""Config-system tests (ref: tests/unit/test_config.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import (DeepSpeedConfig, DeepSpeedConfigError)


def test_batch_reconciliation_full():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_batch_size == 32
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_grad_acc():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
    }, world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_micro():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_only_micro():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.gradient_accumulation_steps == 1


def test_batch_mismatch_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig({
            "train_batch_size": 33,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
        }, world_size=4)


def test_no_batch_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=1)


def test_fp16_and_bf16_conflict():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "fp16": {"enabled": True},
            "bf16": {"enabled": True},
        }, world_size=1)


def test_zero_config_parsing():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
            "stage3_prefetch_bucket_size": 1000,
        },
        "bf16": {"enabled": True},
    }, world_size=1)
    assert cfg.zero.stage == 3
    assert cfg.zero.enabled
    assert cfg.zero.offload_optimizer.device == "cpu"
    assert cfg.zero.offload_optimizer.enabled
    assert not cfg.zero.offload_param.enabled
    assert cfg.zero.stage3_prefetch_bucket_size == 1000


def test_invalid_zero_stage():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"stage": 5}}, world_size=1)


def test_config_from_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 0.001}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_max_lr": 0.001, "warmup_num_steps": 10}},
    }))
    cfg = DeepSpeedConfig(str(p), world_size=2)
    assert cfg.train_batch_size == 16
    assert cfg.optimizer.type == "adamw"
    assert cfg.scheduler.type == "WarmupLR"


def test_duplicate_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_precision_dtype():
    import jax.numpy as jnp
    cfg = DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True}},
                          world_size=1)
    assert cfg.compute_dtype == jnp.bfloat16
    assert cfg.precision_name == "bf16"
