"""Llama-family architecture knobs: rmsnorm + swiglu + no-bias + rotary.

Capability analog of the reference's per-architecture module variants
(ref: module_inject/replace_policy.py — each policy encodes one
transformer dialect); here the dialect is a GPTConfig, so every engine
feature (ZeRO, TP, pipeline, SP, offload) composes with it for free.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _cfg(**kw):
    base = dict(dtype=jnp.float32, use_flash_attention=False, remat=False)
    base.update(kw)
    return gpt.preset("llama-tiny", **base)


def test_param_structure():
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    assert "wpe" not in params                      # rotary, no learned pos
    assert "lm_head" in params                      # untied head
    blk = params["block"]
    assert "mlp_gate" in blk                        # swiglu gate kernel
    assert set(blk["ln1"]) == {"scale"}             # rmsnorm: no bias
    assert set(params["ln_f"]) == {"scale"}
    for name in ("qkv", "attn_out", "mlp_in", "mlp_gate", "mlp_out"):
        assert set(blk[name]) == {"kernel"}, name   # use_bias=False


def test_rmsnorm_matches_manual():
    cfg = _cfg()
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 16))
    scale = jnp.linspace(0.5, 1.5, 16)
    got = gpt._norm(x, {"scale": scale}, cfg)
    ref = (x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True)
                       + cfg.norm_eps)) * scale
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_swiglu_matches_manual():
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    p0 = jax.tree_util.tree_map(lambda x: x[0], params["block"])
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 4, cfg.d_model))
    h = gpt._norm(x + 0, p0["ln2"], cfg)
    up = h @ p0["mlp_in"]["kernel"]
    gate = h @ p0["mlp_gate"]["kernel"]
    manual = (jax.nn.silu(gate) * up) @ p0["mlp_out"]["kernel"]
    # run the whole block and check the MLP branch contributes exactly:
    # block(x) - x - attn_branch == mlp_branch; easier: call _block with
    # attention zeroed via zero qkv weights
    import dataclasses
    pz = dict(p0)
    pz["qkv"] = {"kernel": jnp.zeros_like(p0["qkv"]["kernel"])}
    pz["attn_out"] = {"kernel": jnp.zeros_like(p0["attn_out"]["kernel"])}
    out = gpt._block(x, pz, cfg, deterministic=True)
    # with attn == 0: out = x + mlp(norm(x))  (ln2 of x+0)
    np.testing.assert_allclose(np.asarray(out - x), np.asarray(manual),
                               rtol=2e-5, atol=2e-5)


def test_llama_trains_and_loss_decreases(devices):
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
                "steps_per_print": 1000})
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 65)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0] - 0.3, losses


def test_llama_tensor_parallel_parity(devices):
    """swiglu under TP: the separate gate kernel keeps gate/up halves
    aligned per model-shard — sharded loss equals unsharded."""
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    toks = np.random.default_rng(1).integers(
        0, cfg.vocab_size, (4, 33)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(toks)},
                            jax.random.PRNGKey(0), cfg,
                            deterministic=True))
    mesh = make_mesh(MeshSpec(data=4, model=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 4,
                "mesh": {"tensor_parallel_size": 2,
                         "data_parallel_size": 4},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000},
        mesh=mesh, partition_rules=gpt.gpt_partition_rules())
    got = float(engine.train_batch({"tokens": toks})["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    # the gate kernel really is model-sharded
    gk = engine.state.params["block"]["mlp_gate"]["kernel"]
    assert gk.sharding.shard_shape(gk.shape)[-1] == gk.shape[-1] // 2


def test_llama_gqa_rotary_ring_sp(devices):
    """The llama dialect composes with ring sequence parallelism (GQA
    kv rotation + rotary positions)."""
    cfg = _cfg(max_seq_len=64, sequence_parallel=True, sp_impl="ring")
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    import dataclasses
    cfg = dataclasses.replace(cfg, mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    cfg_dense = dataclasses.replace(cfg, sequence_parallel=False,
                                    mesh=None)
    toks = np.random.default_rng(2).integers(
        0, cfg.vocab_size, (4, 65)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(toks)},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 4,
                "mesh": {"sequence_parallel_size": 4,
                         "data_parallel_size": 2},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000},
        mesh=mesh)
    got = float(engine.train_batch({"tokens": toks})["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_llama_checkpoint_roundtrip(devices, tmp_path):
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ds = {"train_batch_size": 8,
          "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
          "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds)
    toks = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (8, 33)).astype(np.int32)
    engine.train_batch({"tokens": toks})
    engine.save_checkpoint(str(tmp_path))
    next_loss = float(engine.train_batch({"tokens": toks})["loss"])

    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg),
        model_parameters=gpt.init_params(jax.random.PRNGKey(7), cfg),
        config=ds)
    engine2.load_checkpoint(str(tmp_path))
    resumed = float(engine2.train_batch({"tokens": toks})["loss"])
    np.testing.assert_allclose(resumed, next_loss, rtol=1e-5)


def test_llama_decode_matches_full_forward(devices):
    """llama-dialect inference: token-by-token decode (rmsnorm/swiglu/
    no-bias blocks + rotary GQA cache) reproduces full-forward greedy."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    gen = eng.generate(tokens, max_new_tokens=5, temperature=0.0)

    cur = tokens.copy()
    for _ in range(5):
        logits = np.asarray(gpt.forward(params, jnp.asarray(cur), cfg))
        nxt = logits[:, -1].argmax(-1)[:, None].astype(np.int32)
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_llama_pipeline_parity(devices):
    """The llama dialect runs under pipeline parallelism: the shard_map
    spec tree is built from a dialect-preserving dummy config."""
    cfg = _cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    toks = np.random.default_rng(6).integers(
        0, cfg.vocab_size, (8, 33)).astype(np.int32)
    batch = {"tokens": jnp.asarray(toks)}
    ref = float(gpt.loss_fn(params, dict(batch), jax.random.PRNGKey(0),
                            cfg, deterministic=True))
    mesh = make_mesh(MeshSpec(pipe=2, data=-1))
    loss_fn = gpt.make_pipeline_loss_fn(cfg, mesh, num_stages=2,
                                        num_micro=2)
    with jax.set_mesh(mesh):
        got = float(jax.jit(loss_fn)(params, batch, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_hf_llama_injection(devices):
    """HF llama (rmsnorm/swiglu/GQA, split-half rotary) through the
    policy reproduces HF logits — incl. the split-half -> interleaved
    rotary channel permutation of q/k projections."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=64, intermediate_size=172,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rms_norm_eps=1e-6, rope_theta=10000.0)
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    assert eng.cfg.norm == "rmsnorm" and eng.cfg.activation == "swiglu"
    assert eng.cfg.kv_heads == 2 and eng.cfg.rotary_dim == 16
    tokens = np.random.default_rng(0).integers(0, 96, (2, 9)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)

    # and the KV-cache decode path agrees with HF greedy generation
    gen = eng.generate(tokens, max_new_tokens=4, temperature=0.0)
    with torch.no_grad():
        ref = hf_model.generate(
            torch.tensor(tokens.astype(np.int64)), max_new_tokens=4,
            do_sample=False, eos_token_id=None).numpy()
    np.testing.assert_array_equal(gen, ref)


def test_hf_mixtral_injection(devices):
    """HF Mixtral (llama attention + top-2 sparse MoE) through the
    policy reproduces HF logits: the renormalized top-2 softmax equals
    Mixtral's softmax-over-top-k router weights, and the swiglu expert
    stacks map w1/w3/w2 -> wg/wi/wo."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2,
        rms_norm_eps=1e-6, sliding_window=None)
    torch.manual_seed(0)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
    # random-init router logits are near-uniform -> expert choice flips
    # on fp rounding between frameworks; sharpen the router so the test
    # exercises the weight mapping, not tie-breaking
    with torch.no_grad():
        for lyr in hf_model.model.layers:
            lyr.block_sparse_moe.gate.weight *= 40.0

    eng = deepspeed_tpu.init_inference(model=hf_model, dtype=jnp.float32)
    assert eng.cfg.num_experts == 4 and eng.cfg.moe_k == 2
    tokens = np.random.default_rng(0).integers(0, 96, (2, 9)).astype(np.int32)
    ours = np.asarray(eng.forward(tokens))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)


def test_mixtral_finetune_from_hf_checkpoint(devices):
    """The converted Mixtral checkpoint feeds straight into the MoE
    TRAINING path: eval loss matches HF cross-entropy on the same batch
    (rotary now applied in the MoE block; aux weight zeroed and eval
    capacity raised for the no-drop comparison), and a few fine-tuning
    steps decrease it."""
    transformers = pytest.importorskip("transformers")
    import dataclasses
    import torch
    import deepspeed_tpu
    from deepspeed_tpu.models import moe_gpt
    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2,
        rms_norm_eps=1e-6, sliding_window=None)
    torch.manual_seed(1)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for lyr in hf_model.model.layers:
            lyr.block_sparse_moe.gate.weight *= 40.0

    from deepspeed_tpu.inference.policy import resolve_model
    cfg, params = resolve_model(hf_model)
    toks = np.random.default_rng(7).integers(0, 96, (8, 33)).astype(np.int32)

    with torch.no_grad():
        t = torch.tensor(toks.astype(np.int64))
        hf_loss = float(hf_model(t, labels=t).loss)

    cfg_eval = dataclasses.replace(cfg, aux_loss_weight=0.0,
                                   eval_capacity_factor=2.0 * cfg.num_experts)
    loss = float(moe_gpt.loss_fn(
        jax.tree_util.tree_map(lambda x: jnp.asarray(x), params),
        {"tokens": jnp.asarray(toks)}, jax.random.PRNGKey(0), cfg_eval,
        train=False))
    np.testing.assert_allclose(loss, hf_loss, rtol=2e-3)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000})
    losses = [float(engine.train_batch({"tokens": toks})["loss"])
              for _ in range(6)]
    assert losses[-1] < losses[0] - 0.1, losses
