"""dslint v3 tests: the CFG + dataflow core and the flow-sensitive
rules DS015–DS018.

Same three-layer shape as tests/test_dslint_interproc.py:
  1. dataflow machinery — CFG construction units (if/else, while,
     for-else, try/except/finally, early return), gen/kill fixpoint
     convergence on loops, interprocedural pair summaries, and the
     hash-keyed import-graph cache invalidation;
  2. per-rule fixtures — for each of DS015–DS018 at least one
     true-positive package that MUST flag and one clean twin that MUST
     NOT, plus the seeded engine mutation (delete one statement from
     ``_decode_slots_q_fn`` → DS015 catches it);
  3. regressions + self-scan — the real findings this PR fixed stay
     fixed (verify-twin ``impl`` default), and the whole tree lints
     clean under DS015–DS018 in under 15s.
"""

import ast
import json
import subprocess
import sys
import textwrap

from tools.dslint import build_symbol_table
from tools.dslint.core import REPO_ROOT, analyze_package, link_parents
from tools.dslint.dataflow import (DEFAULT_PAIRS, EXC, GenKill,
                                   JitTwinDrift, ResourcePairing,
                                   SnapshotRoundTrip, TracedValueEscape,
                                   build_cfg, build_pair_summaries,
                                   dataflow_rules, solve_forward,
                                   summarize_pairs)
from tools.dslint.symbols import (cache_input_hashes, closure_of,
                                  load_callgraph_cache,
                                  write_callgraph_cache)


def fn_cfg(src):
    tree = ast.parse(textwrap.dedent(src))
    return build_cfg(tree.body[0])


def block_of(cfg, lineno):
    """The block whose statement list carries the stmt at ``lineno``."""
    for b in cfg.blocks:
        for s in b.stmts:
            if getattr(s, "lineno", None) == lineno:
                return b
    raise AssertionError(f"no block holds line {lineno}")


def table_of(files):
    parsed = []
    for path, src in files.items():
        tree = ast.parse(textwrap.dedent(src))
        link_parents(tree)
        parsed.append((path, tree, src.splitlines()))
    return build_symbol_table(parsed)


def rule_hits(rule, files, **kw):
    return rule.check_package(table_of(files), **kw)


# ---------------------------------------------------------------------------
# CFG construction units
# ---------------------------------------------------------------------------

def test_cfg_if_else_branches_and_merge():
    cfg = fn_cfg("""\
        def f(a):
            if a:
                x = 1
            else:
                x = 2
            return x
    """)
    header = block_of(cfg, 2)
    then_b, else_b = block_of(cfg, 3), block_of(cfg, 5)
    assert then_b in header.succ and else_b in header.succ
    merge = block_of(cfg, 6)
    assert merge in then_b.succ and merge in else_b.succ
    # the return reaches the exit
    assert cfg.exit in merge.succ


def test_cfg_if_without_else_falls_through():
    cfg = fn_cfg("""\
        def f(a):
            if a:
                x = 1
            return a
    """)
    header = block_of(cfg, 2)
    after = block_of(cfg, 4)
    # both the taken and the skipped branch reach the merge
    assert after in header.succ
    assert after in block_of(cfg, 3).succ


def test_cfg_while_has_back_edge_and_exit():
    cfg = fn_cfg("""\
        def f(a):
            while a:
                a = a - 1
            return a
    """)
    header = block_of(cfg, 2)
    body = block_of(cfg, 3)
    assert body in header.succ
    assert header in body.succ          # back edge
    assert block_of(cfg, 4) in header.succ


def test_cfg_for_else_runs_on_normal_exit_break_skips_it():
    cfg = fn_cfg("""\
        def f(items):
            for i in items:
                if i:
                    break
            else:
                x = 1
            return 0
    """)
    header = block_of(cfg, 2)
    else_b = block_of(cfg, 6)
    brk = block_of(cfg, 4)
    after = block_of(cfg, 7)
    assert else_b in header.succ        # normal loop exit -> else
    assert after not in header.succ     # ...and ONLY via the else
    assert after in brk.succ            # break jumps past the else
    assert after in else_b.succ


def test_cfg_try_except_finally_edges():
    cfg = fn_cfg("""\
        def f(a):
            try:
                risky(a)
            except ValueError:
                handled(a)
            finally:
                cleanup(a)
            return a
    """)
    body = block_of(cfg, 3)
    handler = block_of(cfg, 5)
    fin = block_of(cfg, 7)
    after = block_of(cfg, 8)
    # the try-body statement may jump to the handler — exceptionally
    assert handler in body.succ and body.succ[handler] == EXC
    # both the normal path and the handler drain through the finally
    assert fin in handler.succ
    assert any(fin in b.succ for b in cfg.blocks
               if b not in (handler, fin))
    assert after in fin.succ
    # an in-flight exception continues past the finally to the exit
    assert cfg.exit in fin.succ and fin.succ[cfg.exit] == EXC


def test_cfg_return_routes_through_finally():
    cfg = fn_cfg("""\
        def f(a):
            try:
                return 1
            finally:
                cleanup(a)
    """)
    ret = block_of(cfg, 3)
    fin = block_of(cfg, 5)
    assert fin in ret.succ              # return runs the finally first


def test_cfg_early_return_leaves_dead_code_unreachable():
    cfg = fn_cfg("""\
        def f(a):
            return a
            x = 1
    """)
    assert cfg.exit in block_of(cfg, 2).succ
    dead = block_of(cfg, 3)
    assert not dead.pred                # island: nothing flows in


def test_cfg_raise_targets_enclosing_handler():
    cfg = fn_cfg("""\
        def f(a):
            try:
                raise ValueError(a)
            except ValueError:
                return 0
    """)
    rais = block_of(cfg, 3)
    handler = block_of(cfg, 5)
    assert handler in rais.succ and rais.succ[handler] == EXC


# ---------------------------------------------------------------------------
# forward solver: gen/kill convergence on loops
# ---------------------------------------------------------------------------

class _Defined(GenKill):
    """Toy may-analysis: names assigned so far."""

    def gen(self, stmt, fact):
        if isinstance(stmt, ast.Assign):
            return {t.id for t in stmt.targets if isinstance(t, ast.Name)}
        return ()


def test_genkill_fixpoint_converges_on_loop():
    cfg = fn_cfg("""\
        def f(a):
            x = 1
            while a:
                y = x
                x = y + 1
            return x
    """)
    in_facts, out_facts = solve_forward(cfg, _Defined())
    # the loop body's facts include its own contribution via the back
    # edge — the fixpoint, not the first pass
    header = block_of(cfg, 3)
    assert {"x", "y"} <= in_facts[header]
    assert {"x", "y"} <= out_facts[cfg.exit] or \
        {"x", "y"} <= in_facts[cfg.exit]


def test_genkill_kill_removes_fact():
    class Tracked(GenKill):
        def gen(self, stmt, fact):
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Constant):
                return {t.id for t in stmt.targets
                        if isinstance(t, ast.Name)}
            return ()

        def kill(self, stmt, fact):
            if isinstance(stmt, ast.Delete):
                return {t.id for t in stmt.targets
                        if isinstance(t, ast.Name)}
            return ()

    cfg = fn_cfg("""\
        def f():
            x = 1
            del x
            return 0
    """)
    _, out_facts = solve_forward(cfg, Tracked())
    assert "x" not in out_facts[cfg.exit]


# ---------------------------------------------------------------------------
# interprocedural pair summaries
# ---------------------------------------------------------------------------

def test_summarize_pairs_counts_sites():
    fn = ast.parse(textwrap.dedent("""\
        def admit(self, rid):
            a = self.cache.allocate(rid, 1)
            b = self.cache.allocate(rid, 2)
            self.cache.free(a)
            row = self.pool.acquire(rid)
            return b, row
    """)).body[0]
    s = summarize_pairs(fn, DEFAULT_PAIRS)
    assert s.acquires["cache-block"] == 2
    assert s.releases["cache-block"] == 1
    assert s.acquires["adapter"] == 1
    assert "adapter" not in s.releases


def test_build_pair_summaries_indexes_by_path_and_name():
    table = table_of({"deepspeed_tpu/a.py": """\
        def take(pool, x):
            h = pool.acquire(x)
            return h


        def give(pool, h):
            pool.release(h)
    """})
    summaries = build_pair_summaries(table)
    assert summaries[("deepspeed_tpu/a.py", "take")].acquires == \
        {"adapter": 1}
    assert summaries[("deepspeed_tpu/a.py", "give")].releases == \
        {"adapter": 1}


# ---------------------------------------------------------------------------
# import-graph cache: content-hash invalidation (satellite)
# ---------------------------------------------------------------------------

_FAKE_INPUTS = {"jit_registry": "aaa", "telemetry_schema": "bbb"}


def _cache_table():
    return table_of({
        "deepspeed_tpu/a.py": "from deepspeed_tpu import b\n",
        "deepspeed_tpu/b.py": "x = 1\n"})


def test_callgraph_cache_round_trips_with_matching_inputs(tmp_path):
    p = tmp_path / "cache.json"
    write_callgraph_cache(_cache_table(), path=p, inputs=_FAKE_INPUTS)
    imports = load_callgraph_cache(p, inputs=_FAKE_INPUTS)
    assert imports                      # hit
    assert closure_of(["deepspeed_tpu/b.py"], imports) == [
        "deepspeed_tpu/a.py", "deepspeed_tpu/b.py"]


def test_callgraph_cache_misses_when_inputs_change(tmp_path):
    p = tmp_path / "cache.json"
    write_callgraph_cache(_cache_table(), path=p, inputs=_FAKE_INPUTS)
    edited = dict(_FAKE_INPUTS, jit_registry="DIFFERENT")
    assert load_callgraph_cache(p, inputs=edited) == {}


def test_callgraph_cache_v1_format_is_stale(tmp_path):
    p = tmp_path / "cache.json"
    p.write_text(json.dumps(
        {"version": 1, "imports": {"a.py": ["b.py"]}}))
    assert load_callgraph_cache(p, inputs=_FAKE_INPUTS) == {}


def test_editing_the_registry_changes_the_cache_key(tmp_path):
    """The satellite's contract end to end: edit jit_registry.py →
    the input hash changes → a cache written before the edit misses."""
    reg = tmp_path / "jit_registry.py"
    reg.write_text((REPO_ROOT / "deepspeed_tpu" / "utils"
                    / "jit_registry.py").read_text())
    files = (("jit_registry", reg),)
    before = cache_input_hashes(files)
    p = tmp_path / "cache.json"
    write_callgraph_cache(_cache_table(), path=p, inputs=before)
    assert load_callgraph_cache(p, inputs=cache_input_hashes(files))

    reg.write_text(reg.read_text()
                   + "\nTWIN_DELTAS['q']['names'] += ('extra',)\n")
    after = cache_input_hashes(files)
    assert after != before
    assert load_callgraph_cache(p, inputs=after) == {}


# ---------------------------------------------------------------------------
# DS015: jit-twin drift
# ---------------------------------------------------------------------------

_TOY_SPEC = (
    (("toy", ("", "_q")),),
    {"q": {"params": ("k_scale",), "names": ("k_scale", "kss"),
           "kwargs": ("k_scale",)}},
)

_TOY_BASE = """\
    def _toy_fn(params, k_pool, tokens):
        x = params + tokens
        y = combine(x, k_pool)
        return y, k_pool
"""


def _toy_pkg(twin):
    # dedent each half separately — concatenating differently-indented
    # literals would nest the twin inside the base function
    return (textwrap.dedent(_TOY_BASE) + "\n\n" + textwrap.dedent(twin))


def test_ds015_clean_twin_collapses_modulo_declared_delta():
    twin = """\
        def _toy_q_fn(params, k_pool, k_scale, tokens):
            x = params + tokens
            kss = rescale(k_scale)
            y = combine(x, k_pool, k_scale=kss)
            return y, k_pool, kss
    """
    hits = rule_hits(JitTwinDrift(spec=_TOY_SPEC), {
        "deepspeed_tpu/inference/engine.py": _toy_pkg(twin)})
    assert hits == []


def test_ds015_statement_drift_outside_delta_flags():
    twin = """\
        def _toy_q_fn(params, k_pool, k_scale, tokens):
            x = params - tokens
            kss = rescale(k_scale)
            y = combine(x, k_pool, k_scale=kss)
            return y, k_pool, kss
    """
    hits = rule_hits(JitTwinDrift(spec=_TOY_SPEC), {
        "deepspeed_tpu/inference/engine.py": _toy_pkg(twin)})
    assert len(hits) == 1
    assert hits[0].rule == "DS015"
    assert "_toy_q_fn" in hits[0].message
    assert "statement 1" in hits[0].message


def test_ds015_missing_statement_flags():
    twin = """\
        def _toy_q_fn(params, k_pool, k_scale, tokens):
            x = params + tokens
            return combine(x, k_pool, k_scale=k_scale), k_pool
    """
    hits = rule_hits(JitTwinDrift(spec=_TOY_SPEC), {
        "deepspeed_tpu/inference/engine.py": _toy_pkg(twin)})
    assert len(hits) == 1
    assert "_toy_q_fn" in hits[0].message


def test_ds015_signature_drift_flags():
    twin = """\
        def _toy_q_fn(params, k_pool, k_scale, tokens, extra):
            x = params + tokens
            y = combine(x, k_pool, k_scale=k_scale)
            return y, k_pool
    """
    hits = rule_hits(JitTwinDrift(spec=_TOY_SPEC), {
        "deepspeed_tpu/inference/engine.py": _toy_pkg(twin)})
    assert len(hits) == 1
    assert "signature" in hits[0].message


def test_ds015_registered_twin_missing_is_a_completeness_finding():
    files = {"deepspeed_tpu/inference/engine.py": _TOY_BASE}
    hits = rule_hits(JitTwinDrift(spec=_TOY_SPEC), files)
    assert len(hits) == 1 and "_toy_q_fn" in hits[0].message
    # targeted/closure runs can't see absence
    assert rule_hits(JitTwinDrift(spec=_TOY_SPEC), files,
                     partial=True) == []


def test_ds015_seeded_mutation_of_decode_slots_q_is_caught():
    """The acceptance bar: delete ONE statement from the real
    ``_decode_slots_q_fn`` body and DS015 must flag the twin."""
    src = (REPO_ROOT / "deepspeed_tpu" / "inference"
           / "engine.py").read_text()
    tree = ast.parse(src)
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef)
              and n.name == "_decode_slots_q_fn")
    # drop the first non-docstring statement (`cfg = self.cfg`)
    del fn.body[1]
    mutated = ast.unparse(tree)
    hits = rule_hits(JitTwinDrift(), {
        "deepspeed_tpu/inference/engine.py": mutated}, partial=True)
    assert any("_decode_slots_q_fn" in h.message for h in hits), \
        [h.message for h in hits]
    # ...and the unmutated engine is clean (the clean-twin direction
    # against the real tree)
    assert rule_hits(JitTwinDrift(), {
        "deepspeed_tpu/inference/engine.py": src}, partial=True) == []


# ---------------------------------------------------------------------------
# DS016: resource pairing
# ---------------------------------------------------------------------------

def test_ds016_early_return_leak_flags():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def admit(self, rid):
                slot = self.cache.allocate(rid)
                if self.full:
                    return None
                self.cache.free(slot)
                return rid
    """}
    hits = rule_hits(ResourcePairing(), files, partial=True)
    assert len(hits) == 1
    assert hits[0].rule == "DS016"
    assert "`slot`" in hits[0].message and "every path" in hits[0].message


def test_ds016_exception_edge_leak_flags():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def admit(self, rid):
                slot = self.cache.allocate(rid)
                try:
                    self.do_setup(rid)
                except ValueError:
                    raise
                self.cache.free(slot)
                return rid
    """}
    hits = rule_hits(ResourcePairing(), files, partial=True)
    assert len(hits) == 1
    assert "exception edge" in hits[0].message


def test_ds016_try_finally_release_is_clean():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def admit(self, rid):
                slot = self.cache.allocate(rid)
                try:
                    self.do_setup(rid)
                finally:
                    self.cache.free(slot)
                return rid
    """}
    assert rule_hits(ResourcePairing(), files, partial=True) == []


def test_ds016_escaped_handle_is_someone_elses_balance():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def admit(self, rid):
                slot = self.cache.allocate(rid)
                self.slots[rid] = slot
                return rid

            def retire(self, rid):
                self.cache.free(self.slots.pop(rid))
    """}
    assert rule_hits(ResourcePairing(), files, partial=True) == []


def test_ds016_double_release_on_some_path_flags():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def drop(self, rid):
                slot = self.cache.allocate(rid)
                if self.fancy:
                    self.cache.free(slot)
                self.cache.free(slot)
    """}
    hits = rule_hits(ResourcePairing(), files, partial=True)
    assert len(hits) == 1
    assert "double release" in hits[0].message


def test_ds016_branch_exclusive_release_is_clean():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def drop(self, rid):
                slot = self.cache.allocate(rid)
                if self.fancy:
                    self.cache.free(slot)
                else:
                    self.cache.free(slot)
    """}
    assert rule_hits(ResourcePairing(), files, partial=True) == []


def test_ds016_package_wide_unbalanced_kind_flags_only_full_tree():
    files = {"deepspeed_tpu/inference/serving.py": """\
        class S:
            def admit(self, rid):
                row = self.pool.acquire(rid)
                self.rows[rid] = row
                return rid
    """}
    full = rule_hits(ResourcePairing(), files)
    assert len(full) == 1
    assert "nothing under deepspeed_tpu/ ever releases" in full[0].message
    assert rule_hits(ResourcePairing(), files, partial=True) == []


# ---------------------------------------------------------------------------
# DS017: traced-value escape
# ---------------------------------------------------------------------------

def test_ds017_branch_on_derived_value_flags():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax
        from functools import partial


        @partial(jax.jit)
        def f(x):
            y = x * 2
            flag = y.sum()
            if flag > 0:
                return y
            return -y
    """}
    hits = rule_hits(TracedValueEscape(), files)
    assert len(hits) == 1
    assert hits[0].rule == "DS017"
    assert "assignment chain" in hits[0].message


def test_ds017_direct_param_branch_is_ds004s_finding_not_ours():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax
        from functools import partial


        @partial(jax.jit)
        def f(x):
            if x > 0:
                return x
            return -x
    """}
    assert rule_hits(TracedValueEscape(), files) == []


def test_ds017_metadata_chain_launders_taint():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax
        from functools import partial


        @partial(jax.jit)
        def f(x):
            s = x.shape
            if s[0] > 4:
                return x * 2
            return x
    """}
    assert rule_hits(TracedValueEscape(), files) == []


def test_ds017_host_sync_on_derived_value_flags():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax
        from functools import partial


        @partial(jax.jit)
        def f(x):
            acc = 0
            for i in range(3):
                acc = acc + x
            v = float(acc)
            return v
    """}
    hits = rule_hits(TracedValueEscape(), files)
    assert len(hits) == 1
    assert "host sync" in hits[0].message


def test_ds017_dict_key_from_traced_value_flags():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax
        from functools import partial


        @partial(jax.jit)
        def f(x):
            k = x + 1
            d = {k: 1}
            return d
    """}
    hits = rule_hits(TracedValueEscape(), files)
    assert len(hits) == 1
    assert "dict key" in hits[0].message


def test_ds017_static_args_stay_host_values():
    files = {"deepspeed_tpu/ops/f.py": """\
        import jax

        def _f(x, mode):
            m = mode + "x"
            if m == "ax":
                return x * 2
            return x

        f = jax.jit(_f, static_argnames=("mode",))
    """}
    assert rule_hits(TracedValueEscape(), files) == []


# ---------------------------------------------------------------------------
# DS018: snapshot round-trip completeness
# ---------------------------------------------------------------------------

_REQ_MOD = """\
    from dataclasses import dataclass

    {allow}

    @dataclass
    class Req:
        rid: str
        out: list = None
        retries: int = 0

        @classmethod
        def from_snapshot(cls, entry):
            return cls(rid=entry["rid"], out=list(entry["out"]))


    def snapshot_entry(req):
        return {{"rid": req.rid, "out": list(req.out)}}
"""


def test_ds018_unserialized_field_flags():
    files = {"deepspeed_tpu/inference/serving.py":
             _REQ_MOD.format(allow="")}
    hits = rule_hits(SnapshotRoundTrip(), files, partial=True)
    assert len(hits) == 1
    assert "`retries`" in hits[0].message
    assert "never serialized" in hits[0].message


def test_ds018_ephemeral_allowlist_silences():
    files = {"deepspeed_tpu/inference/serving.py": _REQ_MOD.format(
        allow='SNAPSHOT_EPHEMERAL = frozenset({"retries"})')}
    assert rule_hits(SnapshotRoundTrip(), files, partial=True) == []


def test_ds018_serialized_but_not_restored_flags():
    files = {"deepspeed_tpu/inference/serving.py": """\
        from dataclasses import dataclass

        @dataclass
        class Req:
            rid: str
            state: str = "queued"

            @classmethod
            def from_snapshot(cls, entry):
                return cls(rid=entry["rid"], state="queued")


        def snapshot_entry(req):
            return {"rid": req.rid, "state": req.state}
    """}
    hits = rule_hits(SnapshotRoundTrip(), files, partial=True)
    assert len(hits) == 1
    assert "never restored" in hits[0].message


def test_ds018_stale_allowlist_entry_flags_on_full_tree_only():
    files = {"deepspeed_tpu/inference/serving.py": _REQ_MOD.format(
        allow='SNAPSHOT_EPHEMERAL = frozenset({"retries", "ghost"})')}
    full = rule_hits(SnapshotRoundTrip(), files)
    assert len(full) == 1 and "`ghost`" in full[0].message
    assert rule_hits(SnapshotRoundTrip(), files, partial=True) == []


def test_ds018_module_without_snapshot_contract_is_ignored():
    files = {"deepspeed_tpu/inference/other.py": """\
        from dataclasses import dataclass

        @dataclass
        class Plain:
            a: int = 0
    """}
    assert rule_hits(SnapshotRoundTrip(), files) == []


# ---------------------------------------------------------------------------
# regressions: the real findings this PR fixed stay fixed
# ---------------------------------------------------------------------------

def test_verify_twins_share_the_impl_default():
    """DS015's first real catch: `_verify_slots_l_fn`/`_verify_slots_ql_fn`
    had dropped the `impl="gather"` default the base (and q twin)
    carry — all four twins must agree."""
    src = (REPO_ROOT / "deepspeed_tpu" / "inference"
           / "engine.py").read_text()
    expected = {"_verify_slots_fn", "_verify_slots_q_fn",
                "_verify_slots_l_fn", "_verify_slots_ql_fn"}
    seen = {}
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.FunctionDef) and node.name in expected:
            args = node.args.args
            defaults = [None] * (len(args) - len(node.args.defaults)) \
                + list(node.args.defaults)
            impl = dict(zip((a.arg for a in args), defaults))["impl"]
            seen[node.name] = getattr(impl, "value", None)
    assert set(seen) == expected
    assert all(v == "gather" for v in seen.values()), seen


def test_serving_snapshot_ephemeral_matches_request_fields():
    """The DS018 allowlist only names real ServeRequest fields (the
    stale-entry direction of the rule, pinned as a plain test too)."""
    from deepspeed_tpu.inference.serving import (SNAPSHOT_EPHEMERAL,
                                                 ServeRequest)
    fields = set(ServeRequest.__dataclass_fields__)
    assert SNAPSHOT_EPHEMERAL <= fields
    # and every non-ephemeral field is in the snapshot dict's keys
    import inspect
    from deepspeed_tpu.inference import serving
    src = inspect.getsource(serving.snapshot_entry)
    for name in fields - SNAPSHOT_EPHEMERAL:
        assert f'"{name}"' in src, name


# ---------------------------------------------------------------------------
# CLI / SARIF integration
# ---------------------------------------------------------------------------

def test_cli_explain_prints_doc_and_example():
    r = subprocess.run(
        [sys.executable, "-m", "tools.dslint", "--explain", "DS016"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0
    assert "DS016" in r.stdout and "resource-pairing" in r.stdout
    assert "minimal true positive" in r.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "tools.dslint", "--explain", "DS099"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert bad.returncode == 2


def test_explain_covers_every_rule():
    from tools.dslint.explain import EXAMPLES, explain
    from tools.dslint.interproc import interproc_catalog
    from tools.dslint.rules import rule_catalog
    for r in rule_catalog() + interproc_catalog():
        assert r["id"] in EXAMPLES
        assert explain(r["id"])


def test_sarif_rules_carry_lintmd_help_anchors():
    from tools.dslint.sarif import to_sarif
    log = to_sarif([], [])
    rules = log["runs"][0]["tool"]["driver"]["rules"]
    by_id = {r["id"]: r for r in rules}
    assert by_id["DS015"]["helpUri"].endswith(
        "#the-flow-sensitive-rules-phase-3")
    assert by_id["DS011"]["helpUri"].endswith(
        "#the-interprocedural-rules-phase-2")
    assert by_id["DS001"]["helpUri"].endswith("#the-rules")
    assert {"DS015", "DS016", "DS017", "DS018"} <= set(by_id)


# ---------------------------------------------------------------------------
# self-scan: the whole tree lints clean under DS015–DS018, fast
# ---------------------------------------------------------------------------

def test_v3_self_scan_clean_and_under_budget():
    stats = {}
    findings = analyze_package(
        [str(REPO_ROOT / "deepspeed_tpu"), str(REPO_ROOT / "tools"),
         str(REPO_ROOT / "tests")],
        rules=[], interproc=dataflow_rules(), stats=stats)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stats["total_s"] < 15.0, stats
