"""Compile-memory guard: analytic estimator calibration + refusal.

The guard exists because borderline-HBM compiles wedge the rig's remote
compile service (PERF.md incident log). These tests pin the estimator to
the measured ground truth: every config that ran fine on the 16GB v5e
must be SAFE, every config that OOM'd or ground the compiler must be
REFUSED. Reference analog: the autotuner prunes by memory model before
launching configs (ref: deepspeed/autotuning/autotuner.py:396).
"""

import jax.numpy as jnp
import pytest

from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import hbm

V5E = 16 * hbm.GiB


def _safe(preset, batch, remat, pol, lc, me, precision="bf16"):
    cfg = gpt.preset(preset, max_seq_len=1024, dtype=jnp.bfloat16,
                     remat=remat, remat_policy=pol, loss_chunk=lc)
    est = hbm.estimate_gpt_train_bytes(cfg, batch, 1024,
                                       precision=precision,
                                       memory_efficient=me)
    ok, msg = hbm.check_compile_safe(est, V5E)
    return ok, est, msg


# (name, preset, batch, remat, policy, loss_chunk, memory_efficient,
#  ran_on_chip) — ground truth from PERF.md round-2 measurements
CALIBRATION = [
    ("b16-full-ce", "gpt2-1.5b", 16, True, "full", 2048, True, True),
    ("b4-full", "gpt2-1.5b", 4, True, "full", 0, True, True),
    ("b16-flashonly", "gpt2-1.5b", 16, True, "flash_only", 2048, True,
     False),  # compile grind, killed the rig twice
    ("b24-full-ce", "gpt2-1.5b", 24, True, "full", 2048, True, False),
    ("b32-full-ce", "gpt2-1.5b", 32, True, "full", 2048, True, False),
    ("b16-sel-ce", "gpt2-1.5b", 16, True, "selective", 2048, True, False),
    ("b4-sel", "gpt2-1.5b", 4, True, "selective", 0, True,
     False),  # OOM: 5.9GB saved activations
    ("med-b8-sel", "gpt2-medium", 8, True, "selective", 0, False, True),
    ("med-b16-ce", "gpt2-medium", 16, True, "selective", 2048, False, True),
    ("med-b8-noremat", "gpt2-medium", 8, False, "selective", 2048, False,
     True),
    ("med-b16-noremat", "gpt2-medium", 16, False, "selective", 2048, False,
     False),  # 12GB activations alone — cannot fit 16GB
]


@pytest.mark.parametrize("name,preset,batch,remat,pol,lc,me,ran",
                         CALIBRATION, ids=[c[0] for c in CALIBRATION])
def test_calibration(name, preset, batch, remat, pol, lc, me, ran):
    ok, est, msg = _safe(preset, batch, remat, pol, lc, me)
    assert ok == ran, f"{name}: guard={ok}, ground truth ran={ran} — {msg}"


def test_selective_width_matches_measured():
    # PERF.md: 1.5B batch-4 selective saved 5.9GB of activations
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="selective")
    est = hbm.estimate_gpt_train_bytes(cfg, 4, 1024,
                                       memory_efficient=True)
    acts = est.contributions["grads_or_acts"]
    assert 4.7 * hbm.GiB < acts < 6.5 * hbm.GiB


def test_flashonly_residual_matches_measured():
    # PERF.md: flash_only saves ~2.6GB of flash residuals beyond full
    cfg_f = gpt.preset("gpt2-1.5b", max_seq_len=1024, remat_policy="full",
                       loss_chunk=2048)
    cfg_o = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                       remat_policy="flash_only", loss_chunk=2048)
    kw = dict(memory_efficient=True)
    delta = (hbm.estimate_gpt_train_bytes(cfg_o, 16, 1024, **kw).total -
             hbm.estimate_gpt_train_bytes(cfg_f, 16, 1024, **kw).total)
    assert 2.0 * hbm.GiB < delta < 3.2 * hbm.GiB


def test_guard_raises_with_context():
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="flash_only", loss_chunk=2048)

    class FakeDev:
        platform, device_kind = "tpu", "TPU v5 lite"

        def memory_stats(self):
            return {}

    with pytest.raises(hbm.MemoryGuardError) as e:
        hbm.guard_gpt_config(cfg, 16, 1024, device=FakeDev(),
                             memory_efficient=True)
    assert "refusing to compile" in str(e.value)
    assert "GiB" in str(e.value)


def test_guard_inactive_off_accelerator():
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="selective")

    class CpuDev:
        platform, device_kind = "cpu", "cpu"

    # unknown/absent HBM -> no refusal (nothing to guard)
    msg = hbm.guard_gpt_config(cfg, 64, 1024, device=CpuDev(),
                               memory_efficient=True)
    assert "guard inactive" in msg


def test_gqa_shrinks_estimate():
    base = gpt.preset("gpt2-medium", max_seq_len=1024)
    gqa = gpt.preset("gpt2-medium", max_seq_len=1024, n_kv_heads=4)
    b = hbm.estimate_gpt_train_bytes(base, 8, 1024).total
    g = hbm.estimate_gpt_train_bytes(gqa, 8, 1024).total
    assert g < b
