"""Compile-memory guard: analytic estimator calibration + refusal.

The guard exists because borderline-HBM compiles wedge the rig's remote
compile service (PERF.md incident log). These tests pin the estimator to
the measured ground truth: every config that ran fine on the 16GB v5e
must be SAFE, every config that OOM'd or ground the compiler must be
REFUSED. Reference analog: the autotuner prunes by memory model before
launching configs (ref: deepspeed/autotuning/autotuner.py:396).
"""

import jax.numpy as jnp
import pytest

from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import hbm

V5E = 16 * hbm.GiB


def _safe(preset, batch, remat, pol, lc, me, precision="bf16"):
    cfg = gpt.preset(preset, max_seq_len=1024, dtype=jnp.bfloat16,
                     remat=remat, remat_policy=pol, loss_chunk=lc)
    est = hbm.estimate_gpt_train_bytes(cfg, batch, 1024,
                                       precision=precision,
                                       memory_efficient=me)
    ok, msg = hbm.check_compile_safe(est, V5E)
    return ok, est, msg


# (name, preset, batch, remat, policy, loss_chunk, memory_efficient,
#  ran_on_chip) — ground truth from PERF.md round-2 measurements
CALIBRATION = [
    ("b16-full-ce", "gpt2-1.5b", 16, True, "full", 2048, True, True),
    ("b4-full", "gpt2-1.5b", 4, True, "full", 0, True, True),
    ("b16-flashonly", "gpt2-1.5b", 16, True, "flash_only", 2048, True,
     False),  # compile grind, killed the rig twice
    ("b24-full-ce", "gpt2-1.5b", 24, True, "full", 2048, True, False),
    ("b32-full-ce", "gpt2-1.5b", 32, True, "full", 2048, True, False),
    ("b16-sel-ce", "gpt2-1.5b", 16, True, "selective", 2048, True, False),
    ("b4-sel", "gpt2-1.5b", 4, True, "selective", 0, True,
     False),  # OOM: 5.9GB saved activations
    ("med-b8-sel", "gpt2-medium", 8, True, "selective", 0, False, True),
    ("med-b16-ce", "gpt2-medium", 16, True, "selective", 2048, False, True),
    ("med-b8-noremat", "gpt2-medium", 8, False, "selective", 2048, False,
     True),
    ("med-b16-noremat", "gpt2-medium", 16, False, "selective", 2048, False,
     False),  # 12GB activations alone — cannot fit 16GB
]


@pytest.mark.parametrize("name,preset,batch,remat,pol,lc,me,ran",
                         CALIBRATION, ids=[c[0] for c in CALIBRATION])
def test_calibration(name, preset, batch, remat, pol, lc, me, ran):
    ok, est, msg = _safe(preset, batch, remat, pol, lc, me)
    assert ok == ran, f"{name}: guard={ok}, ground truth ran={ran} — {msg}"


def test_selective_width_matches_measured():
    # PERF.md: 1.5B batch-4 selective saved 5.9GB of activations
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="selective")
    est = hbm.estimate_gpt_train_bytes(cfg, 4, 1024,
                                       memory_efficient=True)
    acts = est.contributions["grads_or_acts"]
    assert 4.7 * hbm.GiB < acts < 6.5 * hbm.GiB


def test_flashonly_residual_matches_measured():
    # PERF.md: flash_only saves ~2.6GB of flash residuals beyond full
    cfg_f = gpt.preset("gpt2-1.5b", max_seq_len=1024, remat_policy="full",
                       loss_chunk=2048)
    cfg_o = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                       remat_policy="flash_only", loss_chunk=2048)
    kw = dict(memory_efficient=True)
    delta = (hbm.estimate_gpt_train_bytes(cfg_o, 16, 1024, **kw).total -
             hbm.estimate_gpt_train_bytes(cfg_f, 16, 1024, **kw).total)
    assert 2.0 * hbm.GiB < delta < 3.2 * hbm.GiB


def test_guard_raises_with_context():
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="flash_only", loss_chunk=2048)

    class FakeDev:
        platform, device_kind = "tpu", "TPU v5 lite"

        def memory_stats(self):
            return {}

    with pytest.raises(hbm.MemoryGuardError) as e:
        hbm.guard_gpt_config(cfg, 16, 1024, device=FakeDev(),
                             memory_efficient=True)
    assert "refusing to compile" in str(e.value)
    assert "GiB" in str(e.value)


def test_guard_inactive_off_accelerator():
    cfg = gpt.preset("gpt2-1.5b", max_seq_len=1024,
                     remat_policy="selective")

    class CpuDev:
        platform, device_kind = "cpu", "cpu"

    # unknown/absent HBM -> no refusal (nothing to guard)
    msg = hbm.guard_gpt_config(cfg, 64, 1024, device=CpuDev(),
                               memory_efficient=True)
    assert "guard inactive" in msg


def test_gqa_shrinks_estimate():
    base = gpt.preset("gpt2-medium", max_seq_len=1024)
    gqa = gpt.preset("gpt2-medium", max_seq_len=1024, n_kv_heads=4)
    b = hbm.estimate_gpt_train_bytes(base, 8, 1024).total
    g = hbm.estimate_gpt_train_bytes(gqa, 8, 1024).total
    assert g < b


def test_bert_estimator_calibration():
    """bert-large seq128 b256 and seq512 b64 (the bench grid's upper
    rows) must be SAFE on 16GiB with full remat + chunked CE; an absurd
    batch must be REFUSED — so bert_bench's guard keeps the real grid
    runnable while stopping rig-wedging compiles."""
    from deepspeed_tpu.models import bert
    cfg = bert.preset("bert-large", max_seq_len=512, dropout=0.0,
                      dtype=jnp.bfloat16, remat=True, remat_policy="full",
                      loss_chunk=2048)
    for seq, batch in [(128, 256), (128, 512), (512, 32), (512, 64)]:
        est = hbm.estimate_bert_train_bytes(cfg, batch, seq)
        ok, msg = hbm.check_compile_safe(est, V5E)
        assert ok, f"seq{seq} b{batch} must be safe: {msg}"
    est = hbm.estimate_bert_train_bytes(cfg, 4096, 512)
    ok, msg = hbm.check_compile_safe(est, V5E)
    assert not ok, f"b4096 seq512 must be refused: {msg}"


def test_moe_estimator_calibration():
    """The moe_bench grid (12L/768d, E=8/16, b8 seq1024) is SAFE; the
    dispatch working set grows the estimate over dense; a huge
    expert-count config at big batch is REFUSED."""
    from deepspeed_tpu.models import moe_gpt
    cfg = moe_gpt.MoEGPTConfig(n_layers=12, n_heads=12, d_model=768,
                               max_seq_len=1024, dtype=jnp.bfloat16,
                               remat=True, num_experts=8, moe_k=2,
                               capacity_factor=1.25)
    est = hbm.estimate_moe_train_bytes(cfg, 8, 1024)
    ok, msg = hbm.check_compile_safe(est, V5E)
    assert ok, msg
    assert est.contributions["moe_dispatch"] > 0
    dense_like = hbm.estimate_train_bytes(
        n_params=moe_gpt.num_params(cfg), n_layers=cfg.n_layers,
        d_model=cfg.d_model, ffn_dim=cfg.ffn_dim, qkv_dim=cfg.qkv_dim,
        n_heads=cfg.n_heads, vocab_size=cfg.vocab_size, batch=8, seq=1024,
        remat=cfg.remat, remat_policy=cfg.remat_policy,
        loss_chunk=cfg.loss_chunk)
    assert est.total > dense_like.total
    big = moe_gpt.MoEGPTConfig(n_layers=24, n_heads=16, d_model=2048,
                               max_seq_len=2048, dtype=jnp.bfloat16,
                               remat=True, num_experts=64, moe_k=2)
    est = hbm.estimate_moe_train_bytes(big, 32, 2048)
    ok, msg = hbm.check_compile_safe(est, V5E)
    assert not ok, f"64-expert 1.3B-ish at b32 must be refused: {msg}"


def test_moe_num_params_matches_init():
    from deepspeed_tpu.models import moe_gpt
    import jax
    cfg = moe_gpt.MoEGPTConfig(vocab_size=128, n_layers=2, n_heads=2,
                               d_model=32, max_seq_len=64,
                               dtype=jnp.float32, num_experts=4)
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    n = sum(x.size for x in jax.tree.leaves(params))
    assert moe_gpt.num_params(cfg) == n


def test_infer_estimator_calibration():
    """The infer_bench grid (gpt2-medium/large, b8-32, 584-token cache)
    is SAFE; a 32k-cache x 256-batch config is REFUSED (KV cache alone
    exceeds HBM)."""
    cfg = gpt.preset("gpt2-large", max_seq_len=584, dtype=jnp.bfloat16)
    est = hbm.estimate_infer_bytes(cfg, 32, 584)
    ok, msg = hbm.check_compile_safe(est, V5E)
    assert ok, msg
    cfg = gpt.preset("gpt2-large", max_seq_len=32768, dtype=jnp.bfloat16)
    est = hbm.estimate_infer_bytes(cfg, 256, 32768)
    ok, msg = hbm.check_compile_safe(est, V5E)
    assert not ok, msg
    assert est.contributions["kv_cache"] > est.contributions["params"]


class _FakeV5e:
    platform = "tpu"
    device_kind = "TPU v5e"

    def memory_stats(self):
        return {}


def test_guard_wrappers_raise():
    """guard_bert/moe/infer_config raise MemoryGuardError on a v5e-sized
    device for configs past the headroom, and return the decision message
    for safe ones."""
    from deepspeed_tpu.models import bert, moe_gpt
    dev = _FakeV5e()
    bcfg = bert.preset("bert-large", max_seq_len=512, dtype=jnp.bfloat16,
                       remat=True, remat_policy="full", loss_chunk=2048)
    assert "estimated peak" in hbm.guard_bert_config(bcfg, 64, 512,
                                                     device=dev)
    with pytest.raises(hbm.MemoryGuardError):
        hbm.guard_bert_config(bcfg, 4096, 512, device=dev)
    mcfg = moe_gpt.MoEGPTConfig(n_layers=12, n_heads=12, d_model=768,
                                max_seq_len=1024, dtype=jnp.bfloat16,
                                remat=True, num_experts=8)
    assert "estimated peak" in hbm.guard_moe_config(mcfg, 8, 1024,
                                                    device=dev)
    icfg = gpt.preset("gpt2-large", max_seq_len=584, dtype=jnp.bfloat16)
    assert "estimated peak" in hbm.guard_infer_config(icfg, 32, 584,
                                                      device=dev)
    big = gpt.preset("gpt2-large", max_seq_len=32768, dtype=jnp.bfloat16)
    with pytest.raises(hbm.MemoryGuardError):
        hbm.guard_infer_config(big, 256, 32768, device=dev)


# ---------------------------------------------------------------------------
# property-based estimator invariants (hypothesis)
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:  # environment without hypothesis: collect the
    # rest of the module and skip just the property tests
    import pytest as _pytest

    def given(*a, **k):
        return _pytest.mark.skip(reason="hypothesis not installed")

    def settings(*a, **k):
        return lambda f: f

    class _NoStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _NoStrategies()


@settings(max_examples=30, deadline=None)
@given(st.integers(min_value=1, max_value=64),       # batch
       st.sampled_from([256, 1024, 4096]),           # seq
       st.sampled_from(["full", "selective", "flash_only"]),
       st.booleans())                                 # loss chunked?
def test_estimator_monotonicity(batch, seq, pol, chunked):
    """The guard's safety rests on these order relations: more batch/seq
    never estimates SMALLER; 'full' remat never estimates above
    'selective' or no-remat; chunked CE never estimates above dense.
    A violation would let a strictly-bigger program through a guard the
    smaller one failed."""
    cfg = gpt.preset("gpt2-medium", max_seq_len=seq,
                     dtype=jnp.bfloat16, remat=True, remat_policy=pol,
                     loss_chunk=2048 if chunked else 0)
    base = hbm.estimate_gpt_train_bytes(cfg, batch, seq).total
    assert hbm.estimate_gpt_train_bytes(cfg, batch + 1, seq).total >= base
    if seq >= 512:
        assert hbm.estimate_gpt_train_bytes(cfg, batch, seq * 2).total \
            >= base
    import dataclasses
    if pol != "full":
        full = dataclasses.replace(cfg, remat_policy="full")
        assert hbm.estimate_gpt_train_bytes(full, batch, seq).total <= base
    norem = dataclasses.replace(cfg, remat=False)
    assert hbm.estimate_gpt_train_bytes(norem, batch, seq).total >= \
        hbm.estimate_gpt_train_bytes(
            dataclasses.replace(cfg, remat_policy="full"), batch, seq).total
    if chunked:
        dense = dataclasses.replace(cfg, loss_chunk=0)
        assert hbm.estimate_gpt_train_bytes(dense, batch, seq).total >= base
