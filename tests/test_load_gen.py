"""Load-harness tests (tentpole: tools/load_gen.py — the seeded
request generator + drive loop behind the autoscale bench).

Layers:
  1. generation units (pure host) — determinism in the explicit seed,
     Poisson phase structure, per-mix shape contracts (shared prefixes,
     alphabet restriction, length bounds, priority classes);
  2. trace replay — save/load round-trips the population byte-for-byte
     and refuses foreign versions; the CLI writes the same artifact;
  3. drive loop against a real engine — open mode records the full
     per-request timestamp chain (arrival <= submitted <= first_token
     <= finished) and recomputes SLO attainment from it; closed mode
     never exceeds the concurrency bound.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models import gpt
from tools.load_gen import (MIXES, drive, load_trace, main, make_requests,
                            poisson_arrivals, save_trace)

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(scope="module")
def eng():
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_srv(eng, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return ServingEngine(eng, **defaults)


# ---------------------------------------------------------------------------
# generation units
# ---------------------------------------------------------------------------

def test_make_requests_deterministic_in_seed():
    """Same seed => byte-identical population (the DS010 contract
    extended to the harness); a different seed actually differs."""
    kw = dict(mix="chat", phases=[(10, 0.8), (5, 2.0)], vocab_size=128)
    a = make_requests(seed=7, **kw)
    b = make_requests(seed=7, **kw)
    assert json.dumps(a) == json.dumps(b)
    c = make_requests(seed=8, **kw)
    assert json.dumps(a) != json.dumps(c)
    # arrival order + ids are stable
    assert [r["rid"] for r in a] == [f"chat-{i}" for i in range(len(a))]
    assert [r["at"] for r in a] == sorted(r["at"] for r in a)


def test_poisson_arrivals_phase_structure():
    """Rate-0 phases are silent, a high-rate phase is denser than a
    low-rate one, and every instant stays inside the total span."""
    ats = poisson_arrivals([(20, 0.0), (20, 2.0), (20, 0.2)], seed=0)
    assert ats == sorted(ats)
    assert all(20.0 <= t < 60.0 for t in ats)
    spike = sum(1 for t in ats if t < 40.0)
    tail = len(ats) - spike
    assert spike > tail              # 2.0/step vs 0.2/step over 20 steps
    assert poisson_arrivals([(50, 0.0)], seed=0) == []
    assert poisson_arrivals([(20, 1.0)], seed=3) \
        == poisson_arrivals([(20, 1.0)], seed=3)


def test_mix_shape_contracts():
    """Each named mix honours its shape: shared prefixes are common to
    the whole population, the repetitive mix stays inside its tiny
    alphabet, lengths respect their (clipped) bounds, and priorities
    are exactly the two admission classes."""
    for mix, params in MIXES.items():
        reqs = make_requests(seed=0, mix=mix, n=64, vocab_size=128,
                             max_prompt_len=48)
        assert len(reqs) == 64
        components = params.get("components")
        for r in reqs:
            assert 1 <= len(r["prompt"]) <= 48
            assert r["max_new_tokens"] >= 1
            assert r["priority"] in ("interactive", "batch")
            # a composite mix stamps each request with its COMPONENT
            # kind (that is what keys the per-kind SLO budgets); simple
            # mixes stamp their own name
            if components:
                assert r["kind"] in components
            else:
                assert r["kind"] == mix
            assert all(1 <= t < 128 for t in r["prompt"])
        if components:
            # both populations must actually appear, and the override
            # mechanism must bind: rag answers are grounded spans (the
            # (4, 8) floor), never the 2-token ack of the plain rag mix
            kinds = {r["kind"] for r in reqs}
            assert kinds == set(components)
            lo, hi = params["overrides"]["rag"]["new"]
            assert all(lo <= r["max_new_tokens"] <= hi
                       for r in reqs if r["kind"] == "rag")
            continue
        if params["shared_prefix"]:
            lead = reqs[0]["prompt"][:params["shared_prefix"]]
            assert all(r["prompt"][:len(lead)] == lead for r in reqs)
        if params["alphabet"]:
            hi = 1 + params["alphabet"]
            assert all(t < hi for r in reqs for t in r["prompt"])
        batch = sum(r["priority"] == "batch" for r in reqs) / 64
        assert abs(batch - params["batch_frac"]) < 0.25
    with pytest.raises(ValueError):
        make_requests(seed=0, mix="nope", n=4)
    with pytest.raises(ValueError):
        make_requests(seed=0, mix="chat")        # neither n nor phases


def test_trace_round_trip(tmp_path):
    reqs = make_requests(seed=1, mix="rag", phases=[(30, 0.5)])
    path = save_trace(str(tmp_path / "t.json"), reqs, seed=1, mix="rag")
    assert load_trace(path) == reqs
    # a foreign version is refused, not silently replayed
    body = json.load(open(path))
    body["version"] = 99
    json.dump(body, open(path, "w"))
    with pytest.raises(ValueError, match="version"):
        load_trace(path)


def test_cli_writes_replayable_trace(tmp_path, capsys):
    out = tmp_path / "cli.json"
    assert main(["--seed", "3", "--mix", "chat",
                 "--phases", "10:0.5,5:2", "--out", str(out),
                 "--summary"]) == 0
    digest = json.loads(capsys.readouterr().out.splitlines()[-1])
    reqs = load_trace(str(out))
    assert digest["requests"] == len(reqs) > 0
    assert reqs == make_requests(seed=3, mix="chat",
                                 phases=[(10, 0.5), (5, 2.0)])


# ---------------------------------------------------------------------------
# drive loop against a real engine
# ---------------------------------------------------------------------------

def test_drive_open_records_timestamp_chain(eng):
    """Open-loop drive: every request's record carries the full
    arrival <= submitted <= first_token <= finished chain in scheduler
    clock units, and slo_attainment is exactly recomputable from it."""
    entries = make_requests(seed=0, mix="chat", phases=[(12, 0.6)],
                            vocab_size=128, max_prompt_len=20)
    assert entries
    res = drive(mk_srv(eng), entries, mode="open", slo_ttft=8.0)
    assert res["requests"] == len(entries)
    assert len(res["per_request"]) == len(entries)
    for r in res["per_request"]:
        assert r["state"] == "done"
        assert r["arrival"] <= r["submitted_at"] <= r["first_token_at"] \
            <= r["finished_at"]
        assert r["ttft"] == r["first_token_at"] - r["submitted_at"]
        assert r["generated"] > 0
    ttfts = [r["ttft"] for r in res["per_request"]]
    assert res["slo_attainment"] == pytest.approx(
        sum(t <= 8.0 for t in ttfts) / len(entries))
    assert res["ttft_p99"] == pytest.approx(
        float(np.percentile(np.asarray(ttfts), 99)))
    # the drive is deterministic: same seed + same fleet => same record
    res2 = drive(mk_srv(eng), entries, mode="open", slo_ttft=8.0)
    assert res2["per_request"] == res["per_request"]


def test_drive_closed_loop_bounds_inflight(eng):
    """Closed mode ignores arrival times and keeps at most
    ``concurrency`` requests outstanding — provable post-hoc from the
    recorded [submitted, finished) intervals."""
    entries = make_requests(seed=2, mix="chat", n=10, vocab_size=128,
                            max_prompt_len=16)
    res = drive(mk_srv(eng), entries, mode="closed", concurrency=2)
    recs = res["per_request"]
    assert all(r["state"] == "done" for r in recs)
    for t in sorted({r["submitted_at"] for r in recs}):
        inflight = sum(1 for o in recs
                       if o["submitted_at"] <= t < o["finished_at"])
        assert inflight <= 2, t
    with pytest.raises(ValueError, match="open|closed"):
        drive(mk_srv(eng), entries, mode="sideways")
