"""dslint + compile-guard tests (tentpole: tools/dslint +
deepspeed_tpu/utils/compile_guard.py).

Three layers:
  1. per-rule fixtures — for every rule DS001–DS010 one true-positive
     snippet that MUST flag and one clean snippet that MUST NOT (the
     clean twin pins the rule's precision, not just its recall);
  2. machinery — inline suppressions, file-level waivers, the baseline
     multiset roundtrip, CLI exit codes;
  3. self-scan — the repo's own tree must lint clean (zero
     non-baselined findings), which is the acceptance bar that keeps
     the rules honest against real code;
plus unit tests for CompileWatch, the runtime half of the contract.
"""

import json
import subprocess
import sys

import pytest

from tools.dslint import (analyze_paths, analyze_source, apply_baseline,
                          default_rules, load_baseline, rule_catalog,
                          write_baseline)
from tools.dslint.core import REPO_ROOT


def rules_of(src, path="deepspeed_tpu/runtime/sample.py"):
    """Rule ids found in ``src`` linted as if it lived at ``path``
    (the default path is OUTSIDE the DS005-sanctioned env layer)."""
    return sorted({f.rule for f in analyze_source(src, path=path)})


# ---------------------------------------------------------------------------
# per-rule fixtures: one true positive + one clean twin each
# ---------------------------------------------------------------------------

def test_ds001_blocking_sync_in_hot_loop():
    bad = (
        "import jax\n"
        "def train_step(batch):\n"
        "    total = 0.0\n"
        "    for x in batch:\n"
        "        total += float(compute(x))\n"
        "    return total\n")
    assert "DS001" in rules_of(bad)
    # the fix the rule asks for: accumulate on device, one batched pull
    good = (
        "import jax\n"
        "def train_step(batch):\n"
        "    vals = [compute(x) for x in batch]\n"
        "    return sum(jax.device_get(vals))\n")
    assert "DS001" not in rules_of(good)


def test_ds001_only_fires_in_hot_functions():
    # same sync pattern, but not a step/decode/generate-family function
    src = (
        "def summarize(batch):\n"
        "    total = 0.0\n"
        "    for x in batch:\n"
        "        total += float(compute(x))\n"
        "    return total\n")
    assert "DS001" not in rules_of(src)


def test_ds001_comprehension_iterable_is_once_not_per_iteration():
    # jax.device_get as a comprehension's ITERABLE runs once — it is the
    # recommended batched pull, not a per-iteration sync (the shape of
    # inference.engine.generate's fixed `out.extend(... device_get ...)`)
    src = (
        "import jax\n"
        "def decode_step(dev_out):\n"
        "    out = []\n"
        "    out.extend(t * 2 for t in jax.device_get(dev_out))\n"
        "    return out\n")
    assert "DS001" not in rules_of(src)
    # ...but a sync in the comprehension's ELEMENT is per-iteration work
    elem = (
        "import jax\n"
        "def decode_step(vals):\n"
        "    return [float(v) for v in vals]\n")
    assert "DS001" in rules_of(elem)


def test_ds002_jit_lambda_and_jit_in_loop():
    bad = (
        "import jax\n"
        "def bench(xs):\n"
        "    for x in xs:\n"
        "        f = jax.jit(lambda a: a * 2)\n"
        "        f(x)\n")
    found = [f for f in analyze_source(bad, path="m.py") if f.rule == "DS002"]
    msgs = " ".join(f.message for f in found)
    assert "inside a loop" in msgs and "lambda" in msgs
    good = (
        "import jax\n"
        "@jax.jit\n"
        "def f(a):\n"
        "    return a * 2\n"
        "def bench(xs):\n"
        "    for x in xs:\n"
        "        f(x)\n")
    assert "DS002" not in rules_of(good)


def test_ds002_nested_jitted_def_vs_cached():
    bad = (
        "import jax\n"
        "def call(self, p):\n"
        "    @jax.jit\n"
        "    def inner(q):\n"
        "        return q + 1\n"
        "    return inner(p)\n")
    assert "DS002" in rules_of(bad)
    # cached on self: the jitted def survives the call — no per-call key
    good = (
        "import jax\n"
        "def call(self, p):\n"
        "    @jax.jit\n"
        "    def inner(q):\n"
        "        return q + 1\n"
        "    self._fn = inner\n"
        "    return self._fn(p)\n")
    assert "DS002" not in rules_of(good)


def test_ds002_unhashable_static_default():
    bad = (
        "import jax\n"
        "@jax.jit(static_argnums=(1,))\n"
        "def f(x, opts=[]):\n"
        "    return x\n")
    assert "DS002" in rules_of(bad)
    good = (
        "import jax\n"
        "@jax.jit(static_argnums=(1,))\n"
        "def f(x, opts=()):\n"
        "    return x\n")
    assert "DS002" not in rules_of(good)


def test_ds003_read_after_donation():
    bad = (
        "import jax\n"
        "f = jax.jit(g, donate_argnums=(0,))\n"
        "def run(x):\n"
        "    y = f(x)\n"
        "    return x + y\n")
    assert "DS003" in rules_of(bad)
    # rebinding through the consuming call is the sanctioned pattern
    good = (
        "import jax\n"
        "f = jax.jit(g, donate_argnums=(0,))\n"
        "def run(x):\n"
        "    x = f(x)\n"
        "    return x\n")
    assert "DS003" not in rules_of(good)


def test_ds004_traced_python_branch():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n")
    assert "DS004" in rules_of(bad)
    # static args, structure tests, and shape reads stay legal
    good = (
        "import jax\n"
        "@jax.jit(static_argnums=(1,))\n"
        "def step(x, mode):\n"
        "    if mode == 'fast':\n"
        "        return x\n"
        "    if x is None:\n"
        "        return x\n"
        "    if 'mlm' not in x:\n"
        "        return x\n"
        "    if x['a'].shape[0] > 1:\n"
        "        return x\n"
        "    return -x['a']\n")
    assert "DS004" not in rules_of(good)


def test_ds004_sees_through_jit_of_bound_method():
    # self._decode = jax.jit(self._decode_fn, static_argnums=(7,)):
    # call-site positions skip `self`, so arg 7 is the METHOD's 8th
    # non-self parameter
    bad = (
        "import jax\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._d = jax.jit(self._d_fn)\n"
        "    def _d_fn(self, x):\n"
        "        if x > 0:\n"
        "            return x\n"
        "        return -x\n")
    assert "DS004" in rules_of(bad)
    good = (
        "import jax\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._d = jax.jit(self._d_fn, static_argnums=(0,))\n"
        "    def _d_fn(self, impl):\n"
        "        if impl == 'pallas':\n"
        "            return 1\n"
        "        return 0\n")
    assert "DS004" not in rules_of(good)


def test_ds005_env_read_placement():
    src = (
        "import os\n"
        "def pick():\n"
        "    return os.environ.get('DS_THING', '0')\n")
    assert "DS005" in rules_of(src, path="deepspeed_tpu/runtime/zero.py")
    # identical code in the sanctioned config layer is clean
    assert "DS005" not in rules_of(src, path="deepspeed_tpu/runtime/config.py")
    # module-scope reads are flagged EVERYWHERE, even in config
    frozen = "import os\nLEVEL = os.environ.get('DS_LOG', 'info')\n"
    assert "DS005" in rules_of(frozen, path="deepspeed_tpu/runtime/config.py")


def test_ds006_overbroad_except():
    assert "DS006" in rules_of("try:\n    f()\nexcept Exception:\n    pass\n")
    assert "DS006" in rules_of("try:\n    f()\nexcept:\n    pass\n")
    # narrowed type, or a broad catch that at least logs, are clean
    assert "DS006" not in rules_of(
        "try:\n    f()\nexcept OSError:\n    pass\n")
    assert "DS006" not in rules_of(
        "try:\n    f()\nexcept Exception:\n    log('boom')\n")


def test_ds007_mutable_default():
    findings = analyze_source("def f(x, acc=[], *, m={}):\n    return acc\n",
                              path="m.py")
    assert sum(f.rule == "DS007" for f in findings) == 2
    assert "DS007" not in rules_of("def f(x, acc=None):\n    return acc\n")
    # DS007 is the designated autofixable rule
    cat = {r["id"]: r for r in rule_catalog()}
    assert cat["DS007"]["autofixable"] is True


def test_ds008_import_scope_device_work():
    bad = "import jax.numpy as jnp\nZ = jnp.zeros((4,))\n"
    assert "DS008" in rules_of(bad)
    # default-arg expressions evaluate when the top-level def executes
    bad_default = ("import jax.numpy as jnp\n"
                   "def f(x=jnp.zeros(3)):\n    return x\n")
    assert "DS008" in rules_of(bad_default)
    good = ("import jax.numpy as jnp\n"
            "def f():\n    return jnp.zeros((4,))\n")
    assert "DS008" not in rules_of(good)


def test_ds009_non_atomic_pointer_write():
    bad = (
        "import os\n"
        "def point_latest(root, tag):\n"
        "    with open(os.path.join(root, 'latest'), 'w') as f:\n"
        "        f.write(tag)\n")
    assert "DS009" in rules_of(
        bad, path="deepspeed_tpu/runtime/checkpointing.py")
    # the sanctioned shape: stage to a tmp path, then os.replace commits
    good = (
        "import os\n"
        "def point_latest(root, tag):\n"
        "    tmp = os.path.join(root, 'latest.tmp')\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(tag)\n"
        "    os.replace(tmp, os.path.join(root, 'latest'))\n")
    assert "DS009" not in rules_of(
        good, path="deepspeed_tpu/runtime/checkpointing.py")


def test_ds009_scoped_to_checkpoint_paths_and_pointer_files():
    # same in-place write OUTSIDE a checkpoint path: not this rule's beat
    src = (
        "def point_latest(root, tag):\n"
        "    with open(root + '/latest', 'w') as f:\n"
        "        f.write(tag)\n")
    assert "DS009" not in rules_of(src, path="deepspeed_tpu/runtime/zero.py")
    # payload files (non-pointer names) are the manifest's job, not DS009's
    payload = (
        "def dump(root, blob):\n"
        "    with open(root + '/weights.bin', 'wb') as f:\n"
        "        f.write(blob)\n")
    assert "DS009" not in rules_of(
        payload, path="deepspeed_tpu/runtime/checkpointing.py")
    # read-mode opens of the pointer are fine
    read = (
        "def resolve(root):\n"
        "    with open(root + '/latest') as f:\n"
        "        return f.read().strip()\n")
    assert "DS009" not in rules_of(
        read, path="deepspeed_tpu/runtime/checkpointing.py")


def test_ds010_unseeded_randomness_in_inference():
    bad = (
        "import numpy as np\n"
        "def pick(logits):\n"
        "    return int(np.random.randint(0, logits.shape[-1]))\n")
    assert "DS010" in rules_of(bad, path="deepspeed_tpu/inference/x.py")
    bad_key = (
        "import time, jax\n"
        "def fresh_key():\n"
        "    return jax.random.PRNGKey(int(time.time()))\n")
    assert "DS010" in rules_of(bad_key, path="deepspeed_tpu/inference/x.py")
    bad_rs = (
        "import numpy as np\n"
        "def rng():\n"
        "    return np.random.RandomState()\n")
    assert "DS010" in rules_of(bad_rs, path="deepspeed_tpu/inference/x.py")
    # the sanctioned shapes: explicit-seed Generator constructions and
    # counter-based bit generators (the sampling key-chain idiom)
    good = (
        "import numpy as np\n"
        "import jax\n"
        "def draws(seed, pos):\n"
        "    g = np.random.Generator(np.random.Philox(\n"
        "        key=[np.uint64(seed), np.uint64(pos)]))\n"
        "    s = np.random.SeedSequence([seed, 1]).generate_state(1)[0]\n"
        "    r = np.random.default_rng(seed)\n"
        "    k = jax.random.PRNGKey(int(s))\n"
        "    return g.random(2), r.integers(0, 4), k\n")
    assert "DS010" not in rules_of(good, path="deepspeed_tpu/inference/x.py")


def test_ds010_scoped_to_inference_layer():
    # training/data code may want ambient seeding — not this rule's beat
    src = (
        "import numpy as np\n"
        "def shuffle(xs):\n"
        "    np.random.shuffle(xs)\n"
        "    return xs\n")
    assert "DS010" not in rules_of(src, path="deepspeed_tpu/runtime/data.py")
    assert "DS010" in rules_of(src, path="deepspeed_tpu/inference/data.py")


def test_ds000_syntax_error_is_a_finding_not_a_crash():
    findings = analyze_source("def f(:\n", path="m.py")
    assert [f.rule for f in findings] == ["DS000"]


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------

BAD_LOOP = ("def train_step(batch):\n"
            "    t = 0.0\n"
            "    for x in batch:\n"
            "        t += float(compute(x)){trailer}\n"
            "    return t\n")


def test_inline_suppression_trailing_comment():
    assert "DS001" in rules_of(BAD_LOOP.format(trailer=""))
    src = BAD_LOOP.format(
        trailer="  # dslint: disable=DS001 — convergence predicate")
    assert "DS001" not in rules_of(src)


def test_inline_suppression_comment_line_above():
    src = ("def train_step(batch):\n"
           "    t = 0.0\n"
           "    for x in batch:\n"
           "        # dslint: disable=DS001\n"
           "        t += float(compute(x))\n"
           "    return t\n")
    assert "DS001" not in rules_of(src)


def test_inline_suppression_is_rule_specific():
    # suppressing a DIFFERENT rule must not hide the finding
    src = BAD_LOOP.format(trailer="  # dslint: disable=DS006")
    assert "DS001" in rules_of(src)


def test_file_level_suppression():
    src = ("# dslint: disable-file=DS005\n"
           "import os\n"
           "def pick():\n"
           "    return os.environ.get('DS_THING')\n")
    assert "DS005" not in rules_of(src, path="deepspeed_tpu/runtime/zero.py")


# ---------------------------------------------------------------------------
# baseline roundtrip + CLI
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    findings = analyze_source(BAD_LOOP.format(trailer=""), path="m.py")
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(findings, bl_path)
    new, old = apply_baseline(
        analyze_source(BAD_LOOP.format(trailer=""), path="m.py"),
        load_baseline(bl_path))
    assert new == [] and len(old) == len(findings)
    assert all(f.baselined for f in old)
    # the baseline is a MULTISET: a second identical finding is new debt
    doubled = findings + findings
    new2, _ = apply_baseline(doubled, load_baseline(bl_path))
    assert len(new2) == len(findings)


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_LOOP.format(trailer=""))
    empty_bl = tmp_path / "bl.json"
    empty_bl.write_text('{"version": 1, "entries": []}')
    r = subprocess.run(
        [sys.executable, "-m", "tools.dslint", str(bad), "--format", "json",
         "--baseline", str(empty_bl)],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload["counts"]["new"] >= 1
    assert payload["findings"][0]["rule"] == "DS001"
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.dslint", str(clean),
         "--baseline", str(empty_bl)],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0


# ---------------------------------------------------------------------------
# self-scan: the tree this repo ships must lint clean
# ---------------------------------------------------------------------------

def test_self_scan_zero_new_findings():
    findings = analyze_paths([str(REPO_ROOT / "deepspeed_tpu"),
                              str(REPO_ROOT / "tools")])
    new, _ = apply_baseline(findings, load_baseline())
    assert new == [], "non-baselined dslint findings:\n" + "\n".join(
        f.format() for f in new)


def test_every_rule_has_id_and_rationale():
    cat = rule_catalog()
    ids = [r["id"] for r in cat]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert {"DS001", "DS002", "DS003", "DS004", "DS005", "DS006",
            "DS007", "DS008", "DS009", "DS010"} <= set(ids)
    assert all(r["rationale"] for r in cat)
    assert len(default_rules()) == len(cat)


# ---------------------------------------------------------------------------
# CompileWatch: the runtime half of the compile contract
# ---------------------------------------------------------------------------

def test_compile_watch_warm_path_counts_zero(devices):
    import jax.numpy as jnp
    import jax
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    f = jax.jit(lambda x: x * 2)  # dslint: disable=DS002 — fixture jit
    f(jnp.ones((4,)))
    with CompileWatch(max_compiles=0, label="warm") as w:
        for _ in range(4):
            f(jnp.ones((4,)))
    assert w.compiles == 0


def test_compile_watch_detects_recompile(devices):
    import jax.numpy as jnp
    import jax
    from deepspeed_tpu.utils.compile_guard import CompileWatch, RecompileError
    f = jax.jit(lambda x: x + 1)  # dslint: disable=DS002 — fixture jit
    f(jnp.ones((4,)))
    with pytest.raises(RecompileError, match="cold"):
        with CompileWatch(max_compiles=0, label="cold"):
            f(jnp.ones((8,)))  # new shape -> recompile


def test_compile_watch_never_masks_body_exception(devices):
    import jax.numpy as jnp
    import jax
    from deepspeed_tpu.utils.compile_guard import CompileWatch
    f = jax.jit(lambda x: x + 1)  # dslint: disable=DS002 — fixture jit
    with pytest.raises(ValueError, match="boom"):
        with CompileWatch(max_compiles=0):
            f(jnp.ones((16,)))  # WOULD trip the watch...
            raise ValueError("boom")  # ...but the body's error wins


def test_compile_watch_cache_size_fallback(devices, monkeypatch):
    import jax.numpy as jnp
    import jax
    import deepspeed_tpu.utils.compile_guard as cg
    monkeypatch.setattr(cg, "_monitoring_api", lambda: None)
    g = jax.jit(lambda x: x - 1)  # dslint: disable=DS002 — fixture jit
    w = cg.CompileWatch(max_compiles=0)
    w.wrap(g)
    with pytest.raises(cg.RecompileError):
        with w:
            g(jnp.ones((3,)))
    assert not w.monitored and w.compiles >= 1
