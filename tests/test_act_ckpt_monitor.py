"""Activation-checkpointing API, metrics monitor, aio perf sweep
(ref: tests/unit/test_activation_checkpointing.py:290 — checkpoint()
must reproduce the non-checkpointed forward/grads exactly)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import checkpointing
from deepspeed_tpu.utils.monitor import Monitor, NoopMonitor
from tests.simple_model import random_batch, simple_model_loss, simple_model_params


@pytest.fixture(autouse=True)
def _reset_ckpt_config():
    yield
    checkpointing.reset()


# ------------------------------------------------ activation checkpointing

def test_configure_and_is_configured():
    assert not checkpointing.is_configured()
    checkpointing.configure(partition_activations=True, num_checkpoints=4)
    assert checkpointing.is_configured()
    checkpointing.reset()
    assert not checkpointing.is_configured()


def test_configure_from_ds_config_dict():
    checkpointing.configure(deepspeed_config={
        "activation_checkpointing": {"cpu_checkpointing": True,
                                     "number_checkpoints": 2}})
    assert checkpointing._config.checkpoint_in_cpu
    assert checkpointing._config.number_checkpoints == 2


def test_checkpoint_matches_plain_forward_and_grads(rng):
    """checkpoint(fn) must be bit-identical in value and gradient
    (ref: test_activation_checkpointing.py _test_activation_checkpoint)."""
    w1 = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)

    def block(x, w1, w2):
        return jnp.tanh(jnp.tanh(x @ w1) @ w2)

    def loss_plain(w1, w2):
        return jnp.sum(block(x, w1, w2) ** 2)

    def loss_ckpt(w1, w2):
        return jnp.sum(checkpointing.checkpoint(block, x, w1, w2) ** 2)

    checkpointing.configure()  # default: nothing_saveable
    np.testing.assert_allclose(np.asarray(loss_plain(w1, w2)),
                               np.asarray(loss_ckpt(w1, w2)))
    g_plain = jax.grad(loss_plain)(w1, w2)
    g_ckpt = jax.grad(loss_ckpt)(w1, w2)
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_ckpt),
                               rtol=1e-6)


def test_checkpoint_wrapper_under_jit(rng):
    checkpointing.configure()
    w = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)

    @jax.jit  # dslint: disable=DS002 — jitted once per test run; the wrapper-under-jit behavior is what's under test
    def f(w):
        blk = checkpointing.checkpoint_wrapper(lambda a: jnp.sin(a @ a.T))
        return jnp.sum(blk(w))

    assert np.isfinite(float(f(w)))


def test_cpu_offload_policy_with_named_activation(rng):
    """cpu_checkpointing: values tagged checkpoint_name are offloaded to
    pinned host, grads still exact."""
    checkpointing.configure(checkpoint_in_cpu=True, offload_names=("act",))

    w = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)

    def block(x, w):
        h = checkpointing.checkpoint_name(jnp.tanh(x @ w), "act")
        return jnp.sum((h @ w) ** 2)

    def loss(w):
        return checkpointing.checkpoint(block, x, w)

    g = jax.grad(loss)(w)
    g_ref = jax.grad(lambda w: jnp.sum((jnp.tanh(x @ w) @ w) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-5)


def test_manual_seed_shim_raises():
    with pytest.raises(RuntimeError, match="fold_in"):
        checkpointing.model_parallel_cuda_manual_seed(0)


# --------------------------------------------------------------- monitor

def test_monitor_writes_csv_jsonl(tmp_path):
    mon = Monitor(output_path=str(tmp_path), job_name="job", rank=0)
    mon.write_scalars([("Train/loss", 1.5, 10), ("Train/lr", 0.1, 10)])
    mon.write_scalars([("Train/loss", 1.2, 20), ("Train/lr", 0.1, 20)])
    mon.close()
    jsonl = (tmp_path / "job" / "scalars.jsonl").read_text().splitlines()
    assert len(jsonl) == 4
    assert json.loads(jsonl[0]) == {"tag": "Train/loss", "value": 1.5,
                                    "step": 10}
    csv_lines = (tmp_path / "job" / "scalars.csv").read_text().splitlines()
    assert csv_lines[0] == "step,Train/loss,Train/lr"
    assert len(csv_lines) == 3  # header + 2 rows


def test_monitor_resume_no_duplicate_header(tmp_path):
    """A restarted job appending to the same scalars.csv must not inject
    a second header row mid-file."""
    m1 = Monitor(output_path=str(tmp_path), job_name="job", rank=0)
    m1.write_scalars([("loss", 1.0, 1)])
    m1.close()
    m2 = Monitor(output_path=str(tmp_path), job_name="job", rank=0)
    m2.write_scalars([("loss", 0.5, 2)])
    m2.close()
    lines = (tmp_path / "job" / "scalars.csv").read_text().splitlines()
    assert lines[0] == "step,loss"
    assert sum(1 for ln in lines if ln.startswith("step,")) == 1
    assert len(lines) == 3


def test_monitor_nonzero_rank_disabled(tmp_path):
    mon = Monitor(output_path=str(tmp_path), job_name="job", rank=1)
    mon.write_scalars([("x", 1.0, 0)])
    assert not (tmp_path / "job").exists() or \
        not os.listdir(tmp_path / "job")


def test_engine_monitor_integration(tmp_path, devices):
    params = simple_model_params(hidden_dim=16)
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "steps_per_print": 1000,
           "tensorboard": {"enabled": True,
                           "output_path": str(tmp_path / "runs"),
                           "job_name": "t"}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    for i in range(3):
        engine.train_batch(random_batch(8, 16, seed=i))
    # scalars are buffered (no per-step device sync) and flushed on
    # steps_per_print boundaries and close
    engine.destroy()
    jsonl = (tmp_path / "runs" / "t" / "scalars.jsonl").read_text()
    assert jsonl.count("Train/Samples/train_loss") == 3
    assert "Train/Samples/lr" in jsonl


def test_noop_monitor():
    m = NoopMonitor()
    m.write_scalars([("a", 1, 1)])
    m.flush()
    m.close()


# ---------------------------------------------------------- aio sweep

def test_aio_perf_sweep(tmp_path):
    from deepspeed_tpu.ops.aio.perf_sweep import best_aio_config, sweep
    # tmpfs has no O_DIRECT; real runs keep use_direct=True
    records = sweep(str(tmp_path), io_mb=1, use_direct=False,
                    space={"block_size": [128 * 1024],
                           "queue_depth": [4], "thread_count": [1, 2],
                           "op": ["read", "write"]})
    assert len(records) == 4
    ok = [r for r in records if r["gbps"]]
    assert ok, records  # tmpfs: all should succeed
    best = best_aio_config(records)
    assert best["block_size"] == 128 * 1024
    assert "queue_depth" in best
