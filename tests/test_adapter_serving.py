"""Multi-tenant LoRA adapter serving tests (tentpole:
inference/adapters.py + the lora-serve integration in
inference/serving.py — the S-LoRA / Punica workload shape over this
repo's paged continuous-batching stack).

Layers:
  1. adapter-pool unit tests — registration validation, rank-block
     paging, refcount pinning, LRU eviction of released residents,
     exhaustion when every block is pinned;
  2. serving parity — a single unmerged adapter streams token-identical
     to the SAME adapter merged into the weights (``merge_lora``), a
     base-only slot in a lora-on engine stays identical to the
     pre-subsystem base stream, and a heterogeneous batch (two tenants
     + base in one decode batch) matches each tenant's merged
     reference;
  3. lifecycle — eviction/reload round-trips, drain snapshots carrying
     ``adapter_id`` into a fresh engine, failed loads degrading to
     ``state="error"`` (never wrong tokens) with the pool intact;
  4. the compile contract — the ``_l`` program set holds a fixed
     steady-state count with ZERO recompiles across adapter swaps,
     base-only slots and tenants registered after warmup
     (``CompileWatch(0)``), and stays COLD with the subsystem off;
  5. interplay — prefix-cache bypass both ways for adapter-carrying
     requests, speculative decode and int8 KV pools composing with
     adapters, router adapter-affinity dispatch.

One module-scoped engine pair (base + two merged references) backs
every test except the compile contract, which needs unshared jit
caches for its strict cache_size pins.
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.adapters import AdapterLoadError, AdapterPool
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.runtime.lora import (add_lora, adapter_state_dict,
                                        merge_lora)
from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
from deepspeed_tpu.utils.faults import Fault, FaultInjector

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def mk_adapter(params, seed, rank=4):
    """A non-degenerate LoRA export. ``add_lora`` zero-inits B (a no-op
    adapter), so overwrite it with small seeded noise — the adapted
    stream must actually diverge from base for parity to mean much."""
    lp = add_lora(params, rng=jax.random.PRNGKey(seed), rank=rank,
                  alpha=2.0 * rank)
    rng = np.random.default_rng(seed)
    blk = {}
    for t, e in lp["block"].items():
        e = dict(e)
        if "lora_b" in e:
            e["lora_b"] = jnp.asarray(
                rng.standard_normal(e["lora_b"].shape) * 0.05, jnp.float32)
        blk[t] = e
    lp = dict(lp)
    lp["block"] = blk
    return lp


@pytest.fixture(scope="module")
def stack():
    """Shared base engine + two tenant adapters with merged-reference
    engines (static == serving is pinned by test_serving.py, so the
    merged generate() streams anchor the unmerged path transitively)."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    lp1, lp2 = mk_adapter(params, seed=3), mk_adapter(params, seed=4)
    return SimpleNamespace(
        cfg=cfg, params=params, eng=eng, lp1=lp1, lp2=lp2,
        sd1=adapter_state_dict(lp1), sd2=adapter_state_dict(lp2),
        m1=InferenceEngine(config=cfg, params=merge_lora(lp1),
                           dtype=jnp.float32),
        m2=InferenceEngine(config=cfg, params=merge_lora(lp2),
                           dtype=jnp.float32))


def ref_of(eng, p, n):
    return eng.generate(p[None], max_new_tokens=n)[0]


def lora_srv(eng, **kw):
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, lora_serve=True, lora_pool_blocks=2,
                    lora_max_rank=4, lora_rank_block=4)
    defaults.update(kw)
    return ServingEngine(eng, **defaults)


# ---------------------------------------------------------------------------
# adapter-pool unit tests
# ---------------------------------------------------------------------------

def test_adapter_pool_register_validation(stack):
    pool = AdapterPool(stack.eng, pool_blocks=2, max_rank=4, rank_block=4)
    with pytest.raises(ValueError, match="max_rank"):
        pool.register("big", adapter_state_dict(
            mk_adapter(stack.params, seed=1, rank=8)))
    with pytest.raises(ValueError, match="unexpected export key"):
        pool.register("junk", {"not/an/export/key": np.zeros(3)})
    with pytest.raises(ValueError, match="does not expose"):
        pool.register("alien", {"block/warp_core/lora_a": np.zeros(3)})
    with pytest.raises(ValueError, match="missing"):
        pool.register("partial", {k: v for k, v in stack.sd1.items()
                                  if "lora_a" not in k})
    # registration is host-side staging only: no device pool traffic
    pool.register("t0", stack.sd1)
    assert pool.registered() == ["t0"]
    assert pool.stats()["resident"] == 0 and pool.stats()["loads"] == 0


def test_adapter_pool_paging_refcounts_lru_eviction(stack):
    # 2 usable blocks; rank 4 at rank_block 4 -> 1 block per adapter
    pool = AdapterPool(stack.eng, pool_blocks=2, max_rank=4, rank_block=4)
    assert pool.blocks_per_adapter == 1
    for aid, sd in (("t0", stack.sd1), ("t1", stack.sd2),
                    ("t2", stack.sd1)):
        pool.register(aid, sd)
    with pytest.raises(AdapterLoadError):
        pool.acquire("never-registered")
    r0 = pool.acquire("t0")
    assert r0.shape == (1,) and r0[0] > 0    # block 0 is the zero trash
    pool.acquire("t1")
    assert pool.stats()["free_blocks"] == 0 and pool.stats()["loads"] == 2
    # re-acquiring a resident adapter is a HIT (refcount 2, same row)
    assert np.array_equal(pool.acquire("t0"), r0)
    assert pool.stats()["hits"] == 1
    pool.release("t0")                       # rc 2 -> 1: still pinned
    with pytest.raises(AdapterLoadError):
        pool.acquire("t2")                   # every resident is pinned
    pool.release("t0")                       # rc 1 -> 0: LRU-evictable
    pool.acquire("t2")                       # evicts t0, loads t2
    st = pool.stats()
    assert st["evictions"] == 1 and st["loads"] == 3 and st["resident"] == 2
    pool.release("t1")
    pool.acquire("t0")                       # t0 must RELOAD (t1 evicts)
    st = pool.stats()
    assert st["evictions"] == 2 and st["loads"] == 4 and st["hits"] == 1
    with pytest.raises(ValueError):
        pool.release("t1")                   # releasing a non-held pin


# ---------------------------------------------------------------------------
# serving parity: unmerged == merged, base slot == pre-subsystem stream
# ---------------------------------------------------------------------------

def test_serving_lora_single_adapter_bit_parity(stack):
    prompts = prompts_of((5, 9), seed=2)
    ref_m = ref_of(stack.m1, prompts[0], 6)
    ref_b = [ref_of(stack.eng, p, 6) for p in prompts]
    srv = lora_srv(stack.eng)
    srv.register_adapter("t1", stack.sd1)
    out = srv.run([ServeRequest(rid="a", prompt=prompts[0],
                                max_new_tokens=6, adapter_id="t1"),
                   ServeRequest(rid="b", prompt=prompts[1],
                                max_new_tokens=6)])
    np.testing.assert_array_equal(out["a"], ref_m)
    # the base-only slot (all-zeros table row -> trash block, exactly
    # +0.0) stays identical to the engine with no subsystem at all
    np.testing.assert_array_equal(out["b"], ref_b[1])
    assert not np.array_equal(out["a"], ref_b[0])   # adapter is non-trivial
    st = srv.adapters.stats()
    assert st["loads"] == 1 and st["resident"] == 1
    assert srv.stats["adapter_loads"] == 1


def test_serving_lora_heterogeneous_batch_parity(stack):
    """Two tenants + a base request decode in ONE batch; each stream
    matches its own merged-weights reference."""
    prompts = prompts_of((5, 8, 11), seed=5)
    ref1 = ref_of(stack.m1, prompts[0], 6)
    ref2 = ref_of(stack.m2, prompts[1], 6)
    ref_b = ref_of(stack.eng, prompts[2], 6)
    srv = lora_srv(stack.eng, num_slots=3, lora_pool_blocks=3)
    srv.register_adapter("t1", stack.sd1)
    srv.register_adapter("t2", stack.sd2)
    out = srv.run([ServeRequest(rid=0, prompt=prompts[0], max_new_tokens=6,
                                adapter_id="t1"),
                   ServeRequest(rid=1, prompt=prompts[1], max_new_tokens=6,
                                adapter_id="t2"),
                   ServeRequest(rid=2, prompt=prompts[2],
                                max_new_tokens=6)])
    assert srv.stats["peak_occupancy"] == 3     # really one mixed batch
    np.testing.assert_array_equal(out[0], ref1)
    np.testing.assert_array_equal(out[1], ref2)
    np.testing.assert_array_equal(out[2], ref_b)
    assert not np.array_equal(out[0], out[1])   # tenants really diverge


def test_serving_lora_eviction_reload_parity(stack):
    """A pool smaller than the tenant population churns (load -> evict
    -> reload) and every stream still matches its merged reference."""
    prompts = prompts_of((6, 7, 6), seed=8)
    ref1 = [ref_of(stack.m1, prompts[0], 5), ref_of(stack.m1, prompts[2], 5)]
    ref2 = ref_of(stack.m2, prompts[1], 5)
    # ONE usable block and ONE slot: t1 and t2 can never be resident
    # together, so the t1 -> t2 -> t1 sequence forces two evictions
    srv = lora_srv(stack.eng, num_slots=1, lora_pool_blocks=1)
    srv.register_adapter("t1", stack.sd1)
    srv.register_adapter("t2", stack.sd2)
    out = srv.run([ServeRequest(rid="a", prompt=prompts[0],
                                max_new_tokens=5, adapter_id="t1"),
                   ServeRequest(rid="b", prompt=prompts[1],
                                max_new_tokens=5, adapter_id="t2"),
                   ServeRequest(rid="c", prompt=prompts[2],
                                max_new_tokens=5, adapter_id="t1")])
    st = srv.adapters.stats()
    assert st["evictions"] == 2 and st["loads"] == 3 and st["hits"] == 0
    np.testing.assert_array_equal(out["a"], ref1[0])
    np.testing.assert_array_equal(out["b"], ref2)
    np.testing.assert_array_equal(out["c"], ref1[1])
    assert srv.stats["adapter_evictions"] == 2


# ---------------------------------------------------------------------------
# lifecycle: drain snapshots, degraded loads
# ---------------------------------------------------------------------------

def test_serving_lora_snapshot_drain_carries_adapter(stack):
    """pending_snapshot(release=True) releases the adapter pin with the
    KV blocks and round-trips ``adapter_id``; a fresh engine resumes
    the drained request under the SAME adapter, token-identical."""
    p = prompts_of((7,), seed=10)[0]
    ref = ref_of(stack.m1, p, 8)
    srv = lora_srv(stack.eng, spec_decode=False)
    srv.register_adapter("t1", stack.sd1)
    req = ServeRequest(rid="r", prompt=p, max_new_tokens=8,
                       adapter_id="t1")
    srv.submit(req, now=0)
    step = 0
    while srv.busy and len(req.out) < 3:     # drain mid-decode
        srv.step(step)
        step += 1
    snap = srv.pending_snapshot(release=True)
    assert snap[0]["adapter_id"] == "t1"
    assert not srv._slot_arows.any()         # pin gone from the slot map
    st = srv.adapters.stats()
    assert st["resident"] == 1               # released, still warm LRU
    fresh = lora_srv(stack.eng, spec_decode=False)
    fresh.register_adapter("t1", stack.sd1)
    out = fresh.run([ServeRequest.from_snapshot(s) for s in snap])
    np.testing.assert_array_equal(out["r"], ref)


def test_serving_lora_load_fault_degrades_to_error(stack):
    """Every load-failure flavor retires the request with a structured
    ``state="error"`` — never base or another tenant's tokens — while
    co-batched requests keep serving and the pool stays intact."""
    p1, p2 = prompts_of((6, 8), seed=12)
    ref_b = ref_of(stack.eng, p2, 5)
    ref_m = ref_of(stack.m1, p1, 5)
    for kind in ("cache_exhausted", "device_error"):
        inj = FaultInjector([Fault("cache.adapter_load", kind, step=0)],
                            seed=0)
        srv = lora_srv(stack.eng, faults=inj)
        srv.register_adapter("t1", stack.sd1)
        bad = ServeRequest(rid="bad", prompt=p1, max_new_tokens=5,
                           adapter_id="t1")
        ok = ServeRequest(rid="ok", prompt=p2, max_new_tokens=5)
        out = srv.run([bad, ok])
        assert bad.state == "error" and ok.state == "done"
        np.testing.assert_array_equal(out["ok"], ref_b)
        assert srv.stats["adapter_load_errors"] == 1
        # the site fires BEFORE pool state moves: nothing leaked
        st = srv.adapters.stats()
        assert st["resident"] == 0 and st["free_blocks"] == 2
        # the injector window passed: the same tenant loads cleanly now
        retry = ServeRequest(rid="again", prompt=p1, max_new_tokens=5,
                             adapter_id="t1")
        out2 = srv.run([retry])
        assert retry.state == "done"
        np.testing.assert_array_equal(out2["again"], ref_m)


def test_serving_lora_unregistered_and_off_mode(stack):
    p1, p2 = prompts_of((5, 6), seed=14)
    ref_m = ref_of(stack.m1, p2, 4)
    # lora on, id never registered: degrade, the batch keeps serving
    srv = lora_srv(stack.eng)
    srv.register_adapter("t1", stack.sd1)
    ghost = ServeRequest(rid="g", prompt=p1, max_new_tokens=4,
                         adapter_id="nobody")
    real = ServeRequest(rid="r", prompt=p2, max_new_tokens=4,
                        adapter_id="t1")
    out = srv.run([ghost, real])
    assert ghost.state == "error" and real.state == "done"
    np.testing.assert_array_equal(out["r"], ref_m)
    assert srv.stats["adapter_load_errors"] == 1
    # lora OFF (the default): no pool is constructed, registration is a
    # loud error, and a stray adapter_id degrades instead of silently
    # serving base tokens under the tenant's name
    off = ServingEngine(stack.eng, num_slots=1, block_size=4,
                        num_blocks=12, lora_serve=False)
    assert off.adapters is None
    with pytest.raises(ValueError):
        off.register_adapter("t1", stack.sd1)
    stray = ServeRequest(rid="s", prompt=p1, max_new_tokens=4,
                         adapter_id="t1")
    off.run([stray])
    assert stray.state == "error"
    assert off.stats["adapter_load_errors"] == 1


# ---------------------------------------------------------------------------
# the compile contract
# ---------------------------------------------------------------------------

def test_serving_lora_compile_count_contract():
    """Steady state is a FIXED lora program set (one prefill, one
    decode) independent of how many adapters are registered or
    resident: a second workload over two tenants registered AFTER
    warmup — pool eviction churn included — compiles NOTHING. (Fresh
    engines: the strict cache_size pins need unshared jit caches.)"""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    sds = {f"t{i}": adapter_state_dict(mk_adapter(params, seed=20 + i))
           for i in range(4)}
    prompts = prompts_of((10, 9, 7), seed=15)

    def run_workload(aids):
        srv = lora_srv(eng, spec_decode=False)
        for aid in aids:
            srv.register_adapter(aid, sds[aid])
        # two tenants + a base-only slot share the decode batch
        srv.run([ServeRequest(rid=0, prompt=prompts[0], max_new_tokens=8,
                              adapter_id=aids[0]),
                 ServeRequest(rid=1, prompt=prompts[1], max_new_tokens=8,
                              adapter_id=aids[1]),
                 ServeRequest(rid=2, prompt=prompts[2], max_new_tokens=8)])
        return srv

    srv = run_workload(["t0", "t1"])
    quant = srv.kv_quant == "int8"
    pf = eng._prefill_slot_ql if quant else eng._prefill_slot_l
    dc = eng._decode_slots_ql if quant else eng._decode_slots_l
    n_pf, n_dc = cache_size(pf), cache_size(dc)
    if n_pf is not None:
        assert (n_pf, n_dc) == (1, 1), (
            f"lora steady state fragmented: prefill={n_pf} decode={n_dc}")
    watch = CompileWatch(max_compiles=0, label="lora serving steady state")
    watch.wrap(pf)
    watch.wrap(dc)
    with watch:                              # raises on ANY compile
        run_workload(["t2", "t3"])           # fresh tenants, post-warmup
    if n_pf is not None:
        assert cache_size(pf) == 1 and cache_size(dc) == 1
    # the twin split is total: lora-mode serving never touched the base
    # paged programs on this engine...
    assert (cache_size(eng._prefill_slot) or 0) == 0
    assert (cache_size(eng._decode_slots) or 0) == 0
    # ...and with the subsystem off the _l set is never traced at all
    # (the off-mode bit-reference ships zero lora programs)
    eng2 = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    ServingEngine(eng2, num_slots=1, block_size=4, num_blocks=12,
                  lora_serve=False).run(
        [ServeRequest(rid=0, prompt=prompts[2], max_new_tokens=3)])
    assert (cache_size(eng2._prefill_slot_l) or 0) == 0
    assert (cache_size(eng2._decode_slots_l) or 0) == 0


# ---------------------------------------------------------------------------
# interplay: prefix cache, speculative decode, int8 KV, router affinity
# ---------------------------------------------------------------------------

def test_serving_lora_prefix_cache_bypass_both_ways(stack):
    """The prefix index keys blocks by TOKENS only, but an adapter
    slot's K/V embeds that adapter's weights — so adapter-carrying
    requests neither MATCH cached prefixes nor REGISTER their own,
    while base-only traffic keeps sharing."""
    shared = prompts_of((12,), seed=17)[0]   # 3 full blocks of 4
    ref_m = ref_of(stack.m1, shared, 4)
    ref_b = ref_of(stack.eng, shared, 4)
    srv = lora_srv(stack.eng, num_slots=1, prefix_cache=True)
    srv.register_adapter("t1", stack.sd1)
    # base pair first: the second base request hits the cached prefix
    out_b = srv.run([ServeRequest(rid=f"b{i}", prompt=shared.copy(),
                                  max_new_tokens=4) for i in range(2)])
    base_hits = srv.stats["prefix_hits"]
    assert base_hits >= 1
    # adapter pair over the SAME tokens: no match (a base-cached prefix
    # would poison the tenant stream), no registration either
    out_a = srv.run([ServeRequest(rid=f"a{i}", prompt=shared.copy(),
                                  max_new_tokens=4, adapter_id="t1")
                     for i in range(2)])
    assert srv.stats["prefix_hits"] == base_hits
    for i in range(2):
        np.testing.assert_array_equal(out_b[f"b{i}"], ref_b)
        np.testing.assert_array_equal(out_a[f"a{i}"], ref_m)
    assert not np.array_equal(ref_m, ref_b)


def test_serving_lora_spec_decode_compose(stack):
    """Greedy spec-on LoRA serving equals spec-off: drafts are verified
    under the slot's adapter through the _l verify twin."""
    prompts = prompts_of((6, 9), seed=19)
    ref_m = ref_of(stack.m1, prompts[0], 8)
    ref_b = ref_of(stack.eng, prompts[1], 8)
    srv = lora_srv(stack.eng, spec_decode=True)
    srv.register_adapter("t1", stack.sd1)
    out = srv.run([ServeRequest(rid="a", prompt=prompts[0],
                                max_new_tokens=8, adapter_id="t1"),
                   ServeRequest(rid="b", prompt=prompts[1],
                                max_new_tokens=8)])
    np.testing.assert_array_equal(out["a"], ref_m)
    np.testing.assert_array_equal(out["b"], ref_b)


def test_serving_lora_int8_kv_compose(stack):
    """Adapters thread through the int8 KV pool (_ql twins): parity
    against the SAME adapter merged and served over an int8 pool."""
    p = prompts_of((7,), seed=22)[0]
    srv_m = ServingEngine(stack.m1, num_slots=1, block_size=4,
                          num_blocks=12, kv_quant="int8")
    ref = srv_m.run([ServeRequest(rid=0, prompt=p, max_new_tokens=5)])[0]
    srv = lora_srv(stack.eng, num_slots=1, kv_quant="int8")
    srv.register_adapter("t1", stack.sd1)
    out = srv.run([ServeRequest(rid=0, prompt=p, max_new_tokens=5,
                                adapter_id="t1")])
    np.testing.assert_array_equal(out[0], ref)


def test_router_adapter_affinity_dispatch_and_parity(stack):
    """A deadline-free request naming an adapter returns to the replica
    whose pool holds it (a hit, not an H2D reload) under the same
    imbalance cap; deadline traffic goes strictly least-loaded."""
    fleet = [lora_srv(stack.eng, spec_decode=False) for _ in range(2)]
    for rep in fleet:
        rep.register_adapter("t1", stack.sd1)
    router = ReplicaRouter(fleet)
    p_b, p_a = prompts_of((8, 8), seed=24)
    ref_m = ref_of(stack.m1, p_a, 4)
    # seed: base -> replica 0 (tie-break), tenant -> replica 1
    router.submit(ServeRequest(rid="b1", prompt=p_b, max_new_tokens=4))
    router.submit(ServeRequest(rid="a1", prompt=p_a, max_new_tokens=4,
                               adapter_id="t1"))
    assert any(r.rid == "a1" for r in fleet[1].queue)
    # follow-up from the same tenant: affinity beats the least-loaded
    # tie-break (loads are 1 vs 1, which alone would pick replica 0)
    router.submit(ServeRequest(rid="a2", prompt=p_a.copy(),
                               max_new_tokens=4, adapter_id="t1"))
    assert any(r.rid == "a2" for r in fleet[1].queue)
    assert router.stats["adapter_affinity_hits"] >= 1
    # deadline traffic skips affinity: replica 1 is now busier
    router.submit(ServeRequest(rid="a3", prompt=p_a.copy(),
                               max_new_tokens=4, adapter_id="t1",
                               deadline=1e9))
    assert any(r.rid == "a3" for r in fleet[0].queue)
    out = router.run()
    for rid in ("a1", "a2", "a3"):
        np.testing.assert_array_equal(out[rid], ref_m)
    # affinity-routed traffic really lands pool hits on its home
    assert fleet[1].adapters.stats()["hits"] >= 1
