"""Replica-fleet router chaos suite (tentpole: inference/router.py).

Layers:
  1. dispatch units — least-loaded placement, prefix-affinity routing
     for deadline-free traffic, deadline traffic overriding affinity;
  2. the circuit-breaker health machine — healthy -> suspect -> broken
     on consecutive failures, broken -> recovering via checkpointed
     warm restart, recovering -> healthy on a clean probe completion,
     half-open admission caps while recovering;
  3. drain parity under chaos — a replica killed mid-decode (injected
     ``crash`` / ``device_error`` bursts / a watchdog DegradedError, at
     every new ``router.*`` site, fixed seed) drains its in-flight
     snapshot onto survivors, and every non-shed request's final
     tokens are IDENTICAL to an undisturbed solo greedy run (the
     acceptance gate);
  4. total degrade — all replicas broken raises ONE fleet-level
     DegradedError whose merged results + pending cover every rid;
  5. the compile contract — N replicas sharing one InferenceEngine
     hold the 2-program / zero-recompile steady state under active
     chaos (CompileWatch(0)).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.router import (BROKEN, HEALTHY, RECOVERING,
                                            SUSPECT, ReplicaRouter)
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine)
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils.faults import Fault, FaultInjector

pytestmark = pytest.mark.usefixtures("devices")


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


@pytest.fixture(scope="module")
def eng():
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def mk_fleet(eng, n=3, **kw):
    """N replicas sharing ONE InferenceEngine — per-instance jits, so
    the whole fleet shares the same compiled serving programs."""
    defaults = dict(num_slots=2, block_size=4, num_blocks=24,
                    prefill_chunk=8, spec_decode=False)
    defaults.update(kw)
    return [ServingEngine(eng, **defaults) for _ in range(n)]


def mk_reqs(prompts, n=6, **kw):
    return [ServeRequest(rid=i, prompt=p, max_new_tokens=n, **kw)
            for i, p in enumerate(prompts)]


# ---------------------------------------------------------------------------
# dispatch units
# ---------------------------------------------------------------------------

def test_router_dispatch_least_loaded(eng):
    """A fresh request lands on the replica with the most headroom
    (queue depth + occupied slots), tie-broken by index."""
    fleet = mk_fleet(eng, n=2)
    router = ReplicaRouter(fleet)
    p = prompts_of((6, 7, 8, 9), seed=3)
    # preload replica 0 with two requests behind the router's back
    fleet[0].submit(ServeRequest(rid="x0", prompt=p[0]))
    fleet[0].submit(ServeRequest(rid="x1", prompt=p[1]))
    router.submit(ServeRequest(rid="a", prompt=p[2]))
    assert any(r.rid == "a" for r in fleet[1].queue)
    # loads now 2 vs 1 -> next also goes to replica 1
    router.submit(ServeRequest(rid="b", prompt=p[3]))
    assert any(r.rid == "b" for r in fleet[1].queue)
    # balanced again -> tie-break picks replica 0
    router.submit(ServeRequest(rid="c", prompt=prompts_of((5,), seed=8)[0]))
    assert any(r.rid == "c" for r in fleet[0].queue)
    assert router.stats["dispatched"] == 3


def test_router_dispatch_prefix_affinity_and_deadline(eng):
    """Deadline-free same-prefix traffic returns to the replica whose
    prefix blocks are warm; deadline traffic goes strictly
    least-loaded even when affinity points elsewhere."""
    fleet = mk_fleet(eng, n=2)
    router = ReplicaRouter(fleet)
    sys_a, sys_b = prompts_of((20, 20), seed=5)
    # first arrivals seed the affinity map: B -> replica 0 (tie-break),
    # A -> replica 1 (least loaded)
    router.submit(ServeRequest(rid="b1", prompt=sys_b))
    router.submit(ServeRequest(rid="a1", prompt=sys_a))
    assert any(r.rid == "a1" for r in fleet[1].queue)
    # same-prefix follow-up: affinity beats the least-loaded tie-break
    # (loads are 1 vs 1, so least-loaded alone would pick replica 0)
    router.submit(ServeRequest(rid="a2", prompt=sys_a.copy()))
    assert any(r.rid == "a2" for r in fleet[1].queue)
    assert router.stats["affinity_hits"] >= 1
    # a deadline-carrying request with the SAME prefix skips affinity:
    # replica 1 now holds 2 requests, replica 0 holds 1
    router.submit(ServeRequest(rid="a3", prompt=sys_a.copy(),
                               deadline=1e9))
    assert any(r.rid == "a3" for r in fleet[0].queue)


def test_router_prefix_affinity_warms_shared_blocks(eng):
    """With the prefix cache on, affinity-routed traffic actually hits
    shared blocks on its home replica."""
    fleet = mk_fleet(eng, n=2, prefix_cache=True, num_blocks=32)
    router = ReplicaRouter(fleet)
    sys_p = prompts_of((16,), seed=6)[0]
    tails = prompts_of((4, 4, 4), seed=7)
    reqs = [ServeRequest(rid=i, prompt=np.concatenate([sys_p, t]),
                         max_new_tokens=4) for i, t in enumerate(tails)]
    refs = _solo_refs(eng, [r.prompt for r in reqs], 4)
    # serialize arrivals so each later request sees the published prefix
    router.submit(reqs[0])
    out = router.run()
    for r in reqs[1:]:
        router.submit(r)
        out.update(router.run())
    home = router._affinity[router._affinity_key(sys_p)]
    assert fleet[home].stats["prefix_hits"] >= 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


# ---------------------------------------------------------------------------
# circuit-breaker health machine
# ---------------------------------------------------------------------------

def test_router_breaker_state_machine(eng):
    """healthy -> suspect on one failure, back to healthy on a clean
    step, broken at the consecutive-failure threshold — and the broken
    replica's work drains onto the survivor with token parity."""
    inj = FaultInjector(
        [Fault("router.step", "device_error", step=0),
         Fault("router.step", "device_error", step=2, count=2)], seed=0)
    fleet = mk_fleet(eng, n=2, faults=inj)
    router = ReplicaRouter(fleet, breaker_threshold=2, faults=inj)
    prompts = prompts_of((6, 9), seed=11)
    refs = _solo_refs(eng, prompts, 8)
    reqs = mk_reqs(prompts, n=8)
    # both requests to replica 0: submit directly so only r0 is busy
    # (router.step visits then target r0 alone -> deterministic)
    fleet[0].submit(reqs[0])
    fleet[0].submit(reqs[1])
    router.step()                       # visit 0: failure
    assert router.health() == [SUSPECT, HEALTHY]
    router.step()                       # visit 1: clean
    assert router.health() == [HEALTHY, HEALTHY]
    router.step()                       # visit 2: failure
    assert router.health() == [SUSPECT, HEALTHY]
    router.step()                       # visit 3: threshold -> broken
    assert router.health() == [BROKEN, HEALTHY]
    assert router.stats["breaker_trips"] == 1
    assert router.stats["drained_requests"] == 2
    out = router.run()
    assert len(inj.fired) == 3
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert all(r.state == "done" for r in fleet[1].finished)


def test_router_recovering_half_open_admissions(eng):
    """A recovering replica admits at most probe_admissions in-flight
    requests; overflow routes to healthy replicas."""
    fleet = mk_fleet(eng, n=2)
    router = ReplicaRouter(
        fleet, probe_admissions=1,
        replica_factory=lambda i, tag: mk_fleet(eng, n=1)[0])
    router.replicas[0].health = BROKEN       # unit-level: force the state
    router.restart_replica(0)
    assert router.health() == [RECOVERING, HEALTHY]
    p = prompts_of((5, 6, 7), seed=13)
    router.submit(ServeRequest(rid="p0", prompt=p[0]))   # probe -> r0
    assert any(r.rid == "p0" for r in router.replicas[0].srv.queue)
    # half-open window full: the rest go to the healthy replica even
    # though r0 has equal-or-less load
    router.submit(ServeRequest(rid="p1", prompt=p[1]))
    router.submit(ServeRequest(rid="p2", prompt=p[2]))
    assert {r.rid for r in fleet[1].queue} == {"p1", "p2"}
    out = router.run()
    # the probe completed cleanly -> breaker closes
    assert router.health() == [HEALTHY, HEALTHY]
    assert set(out) == {"p0", "p1", "p2"}


def test_router_warm_restart_checkpoint_walkback(eng, tmp_path):
    """restart_replica resolves the newest VALID checkpoint tag with
    walk-back semantics: a torn `latest` tag is skipped, the factory
    gets the newest tag that validates, and the rebuilt replica
    rejoins through recovering to healthy."""
    root = tmp_path / "ckpts"
    good = root / "t_good" / "state"
    good.mkdir(parents=True)                  # legacy-valid tag
    time.sleep(0.01)
    (root / "t_torn").mkdir()                 # no state dir: invalid
    (root / "latest").write_text("t_torn")    # pointer at the torn tag
    calls = []

    def factory(idx, tag):
        calls.append((idx, tag))
        return mk_fleet(eng, n=1)[0]

    inj = FaultInjector([Fault("router.step", "crash", step=1)], seed=0)
    fleet = mk_fleet(eng, n=2, faults=inj)
    router = ReplicaRouter(fleet, replica_factory=factory,
                           ckpt_dir=str(root), faults=inj)
    prompts = prompts_of((7, 8), seed=17)
    refs = _solo_refs(eng, prompts, 6)
    out = router.run(mk_reqs(prompts, n=6))
    assert router.health().count(BROKEN) == 1
    broken = router.health().index(BROKEN)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    # warm restart: newest valid tag, NOT the torn latest
    tag = router.restart_replica(broken)
    assert tag == "t_good" and calls == [(broken, "t_good")]
    assert router.health()[broken] == RECOVERING
    # a probe request completes on the rebuilt replica -> healthy
    probe = ServeRequest(rid="probe", prompt=prompts_of((5,), seed=19)[0],
                         max_new_tokens=4)
    # point dispatch at the recovering replica by loading the other one
    fleet = [rep.srv for rep in router.replicas]
    fleet[1 - broken].submit(ServeRequest(
        rid="ballast", prompt=prompts_of((5,), seed=23)[0],
        max_new_tokens=4))
    router.submit(probe)
    assert any(r.rid == "probe"
               for r in router.replicas[broken].srv.queue)
    router.run()
    assert router.health()[broken] == HEALTHY
    assert router.stats["restarts"] == 1


# ---------------------------------------------------------------------------
# drain parity under chaos (the acceptance gate)
# ---------------------------------------------------------------------------

def _parity_run(eng, faults, n_replicas=3, n_reqs=6, max_new=8, **fleet_kw):
    """Run a fleet under the given injected faults; assert every
    request finishes done with tokens identical to a solo greedy run."""
    prompts = prompts_of(tuple(5 + (i % 4) * 3 for i in range(n_reqs)),
                         seed=29)
    refs = _solo_refs(eng, prompts, max_new)
    inj = FaultInjector(faults, seed=0)
    fleet = mk_fleet(eng, n=n_replicas, faults=inj, **fleet_kw)
    router = ReplicaRouter(fleet, faults=inj)
    out = router.run(mk_reqs(prompts, n=max_new))
    assert inj.fired, "the chaos never actually fired"
    assert set(out) == set(range(n_reqs))
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            out[i], ref,
            err_msg=f"request {i} lost drain parity under {faults}")
    return router


def test_router_drain_parity_crash_mid_decode(eng):
    """The headline acceptance: 3 replicas, one killed mid-decode by an
    injected crash — every request completes token-identical to an
    undisturbed run, with >=1 request actually drained."""
    router = _parity_run(
        eng, [Fault("router.step", "crash", step=7)])
    assert router.health().count(BROKEN) == 1
    assert router.stats["drained_requests"] >= 1
    assert router.stats["breaker_trips"] == 1


def test_router_drain_parity_device_error_burst(eng):
    """A burst of transient step failures trips the breaker (threshold
    crossings, not one-off crashes) and drains with parity."""
    # 7 consecutive failures round-robin across 3 replicas: one replica
    # takes 3 strikes (-> broken), the others 2 (-> recover on the next
    # clean step); 9+ would be 3 strikes everywhere = total degrade
    router = _parity_run(
        eng, [Fault("router.step", "device_error", step=6, count=7)])
    assert router.stats["breaker_trips"] >= 1
    assert router.stats["drained_requests"] >= 1


def test_router_drain_parity_watchdog_degraded(eng):
    """A replica's own watchdog DegradedError (driven by an injected
    slow decode) is absorbed by the router: break, drain, parity."""
    # grace=1: serving.decode visits are fleet-global (shared injector),
    # so consecutive slow visits can straddle two replicas and a grace
    # of 2 would never accumulate on either
    router = _parity_run(
        eng,
        [Fault("serving.decode", "slow", step=5, param=0.05)],
        step_time_budget_s=0.01, watchdog_grace=1)
    assert router.health().count(BROKEN) == 1
    assert router.stats["drained_requests"] >= 1


def test_router_drain_parity_dispatch_site_faults(eng):
    """Faults at router.dispatch fire BEFORE the submit: a transient
    retries on the next-best replica, a crash kills the chosen replica
    (draining whatever it held) — parity either way."""
    router = _parity_run(
        eng, [Fault("router.dispatch", "device_error", step=1),
              Fault("router.dispatch", "crash", step=4)])
    assert router.stats["redispatches"] >= 1
    assert router.health().count(BROKEN) == 1


def test_router_drain_parity_drain_site_transient(eng):
    """A transient fault at router.drain retries the drain (it fires
    before any snapshot state moves) — nothing lost, parity holds."""
    router = _parity_run(
        eng, [Fault("router.step", "crash", step=7),
              Fault("router.drain", "device_error", step=0)])
    assert router.stats["drained_requests"] >= 1


def test_router_all_broken_total_degrade(eng):
    """Every replica broken: ONE fleet-level DegradedError carrying
    merged results plus pending entries — results ∪ pending covers
    every submitted rid, and nothing is double-reported."""
    prompts = prompts_of((6, 9, 12, 5, 8), seed=31)
    inj = FaultInjector(
        [Fault("router.step", "crash", step=4, count=1000)], seed=0)
    fleet = mk_fleet(eng, n=3, faults=inj)
    router = ReplicaRouter(fleet, faults=inj)
    with pytest.raises(DegradedError) as ei:
        router.run(mk_reqs(prompts, n=8))
    e = ei.value
    assert router.health() == [BROKEN, BROKEN, BROKEN]
    assert router.stats["fleet_degraded"] >= 1
    done = set(e.results)
    pending = {s["rid"] for s in e.pending}
    assert done | pending == set(range(len(prompts)))
    assert not (done & pending)
    # pending entries are cold-resume complete: a fresh single engine
    # finishes them with exact parity (the drain foundation)
    refs = _solo_refs(eng, prompts, 8)
    fresh = mk_fleet(eng, n=1)[0]
    out = fresh.run([ServeRequest.from_snapshot(s) for s in e.pending])
    out.update(e.results)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


# ---------------------------------------------------------------------------
# compile contract
# ---------------------------------------------------------------------------

def test_router_compile_contract_under_chaos():
    """N replicas sharing one InferenceEngine share its per-instance
    jitted programs: after warmup the fleet steady state is the same
    1 prefill + 1 decode executable, and a full chaos run (crash +
    drain + redispatch) compiles NOTHING new."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size

    # fresh engine: the module fixture's jit caches carry extra pool
    # shapes from tests that use different num_blocks
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)

    def run_workload(faults):
        inj = FaultInjector(faults, seed=0)
        fleet = mk_fleet(eng, n=3, faults=inj)
        router = ReplicaRouter(fleet, faults=inj)
        prompts = prompts_of((5, 9, 12, 7), seed=37)
        out = router.run(mk_reqs(prompts, n=8))
        return router, out

    run_workload([])                        # warmup: compile everything
    quant = mk_fleet(eng, n=1)[0].kv_quant == "int8"
    pf = eng._prefill_slot_q if quant else eng._prefill_slot
    dc = eng._decode_slots_q if quant else eng._decode_slots
    n_prefill, n_decode = cache_size(pf), cache_size(dc)
    if n_prefill is not None:
        assert (n_prefill, n_decode) == (1, 1), (
            f"fleet steady state fragmented: prefill={n_prefill} "
            f"decode={n_decode} programs (expected 1+1)")
    watch = CompileWatch(max_compiles=0, label="router steady state")
    watch.wrap(pf)
    watch.wrap(dc)
    with watch:                             # raises RecompileError if
        router, _ = run_workload(           # chaos causes ANY compile
            [Fault("router.step", "crash", step=7),
             Fault("router.dispatch", "device_error", step=9)])
    assert router.stats["drained_requests"] >= 1
