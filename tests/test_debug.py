"""Debug helpers (ref: deepspeed/utils/debug.py param-name mapping)."""

import numpy as np

from deepspeed_tpu.utils import debug


def test_param_names_and_summary():
    tree = {"wte": {"embedding": np.ones((4, 8), np.float32)},
            "block": {"qkv": {"kernel": np.zeros((2, 8, 24), np.float32)}}}
    names = debug.param_names(tree)
    assert set(names) == {"wte/embedding", "block/qkv/kernel"}
    s = debug.module_summary(tree)
    assert "total parameters: 416" in s


def test_debug_param_probe():
    tree = {"w": np.full((3, 3), 2.0, np.float32)}
    p = debug.debug_param(tree, "w")
    assert "mean=2.000e+00" in p
    assert debug.debug_param(tree, "missing") is None
