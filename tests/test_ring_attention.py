"""Ring-attention (sequence parallelism) tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.attention.flash import mha_reference
from deepspeed_tpu.ops.attention.ring import ring_attention
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _qkv(B=2, S=64, H=2, D=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(devices, causal):
    q, k, v = _qkv()
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_grads_match_dense(devices):
    q, k, v = _qkv(B=1, S=32, H=2, D=8)
    mesh = make_mesh(MeshSpec(data=1, sequence=8))

    g_ring = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, mesh, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_ring_with_data_parallel_axes(devices):
    """sequence=4 combined with data=2."""
    q, k, v = _qkv(S=32)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sequence_parallel_gpt_trains(devices):
    """GPT with sequence_parallel: loss matches dense-GPT loss and trains."""
    from deepspeed_tpu.models import gpt
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    cfg_dense = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                              d_model=32, max_seq_len=64,
                              use_flash_attention=False, remat=False,
                              dtype=jnp.float32)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 65)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(tokens)},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))

    ds = {"train_batch_size": 8,
          "mesh": {"sequence_parallel_size": 4},
          "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
          "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds,
        mesh=mesh)
    losses = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(8)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[-1] < losses[0] - 0.3
    # divisible token arrays get sequence-sharded (the 65-long shifted input
    # intentionally stays batch-only)
    sharded = engine._shard_batch({"x": tokens[:, :64]})
    assert sharded["x"].sharding.shard_shape((8, 64))[1] == 16


def test_ring_gqa_matches_dense(devices):
    """GQA under ring SP: the small grouped k/v rotate; repeated locally
    per step — matches the dense grouped reference, forward AND grads
    (training with SP + GQA is now allowed)."""
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_r(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_d(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gr, gd, "qkv"):
        assert a.shape == b.shape, n
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=n)


def test_ring_segments_match_dense(devices):
    """Packed segment_ids under the ring: the metadata rotates with its
    K/V block, so block-diagonal masking is exact."""
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(np.repeat(np.arange(4), 16)[None].repeat(2, 0),
                       jnp.int32)
    out = ring_attention(q, k, v, mesh, causal=True, segment_ids=segs)
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_kv_mask_matches_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    r = np.random.default_rng(3)
    mask_np = (r.random((2, 64)) > 0.25).astype(np.float32)
    mask = jnp.asarray(mask_np)
    out = ring_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    ref = mha_reference(q, k, v, causal=True, kv_mask=mask)
    # rows with NO causally-visible valid key are garbage-by-contract
    # (dense: uniform average over all keys; ring: exact 0 — it skips
    # above-diagonal blocks) — compare only defined rows, and pin the
    # ring's documented contract for the rest
    defined = np.cumsum(mask_np, axis=1) > 0              # [B, S]
    np.testing.assert_allclose(np.asarray(out)[defined],
                               np.asarray(ref)[defined],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out)[~defined], 0.0, atol=1e-6)


def test_ring_window_matches_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, window=16)
    ref = mha_reference(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_packed_grads_match_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (1, 32, 8, 8), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(np.repeat(np.arange(2), 16)[None], jnp.int32)
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=nm)


def test_ring_multichunk_matches_dense(devices):
    """chunk < S_loc exercises the chunked online-softmax path (the
    fallback's whole point: O(S_loc*chunk) local memory, never the dense
    O(S_loc^2) score matrix), forward and grads."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (1, 64, 2, 8), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, chunk=4)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, chunk=4) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=nm)


def test_ring_window_multichunk_matches_dense(devices):
    """Sliding window + chunked local path + the static early-stop of the
    rotation chain (window=24 over S_loc=16 -> 3 hops, not 4)."""
    from deepspeed_tpu.ops.attention.ring import _num_steps
    assert _num_steps(4, 16, True, 24) == 3
    assert _num_steps(8, 8, True, 8) == 2
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q, k, v = (jax.random.normal(kk, (1, 64, 2, 8), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, window=24, chunk=8)
    ref = mha_reference(q, k, v, causal=True, window=24)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_flash_kernel_matches_dense(devices, pallas_interpret):
    """use_flash=True routes every ring step through the Pallas flash
    kernel (interpret mode on CPU): parity incl. grads, GQA, packing."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    B, S, H, Hkv, D = 1, 256, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    segs = jnp.asarray(
        np.repeat(np.arange(4), 64)[None].astype(np.int32))
    out = ring_attention(q, k, v, mesh, causal=True, use_flash=True,
                         block_q=32, block_kv=32, segment_ids=segs)
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, use_flash=True, block_q=32,
        block_kv=32, segment_ids=segs) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=nm)


def test_ring_flash_window_matches_dense(devices, pallas_interpret):
    """Flash-kernel ring steps with a sliding window: the banded partial
    block (static q_off) goes through the kernel's offset index maps."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q, k, v = (jax.random.normal(kk, (1, 256, 2, 8), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, use_flash=True,
                         block_q=32, block_kv=32, window=96)
    ref = mha_reference(q, k, v, causal=True, window=96)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # grads too: the q_off-shifted windowed BACKWARD index maps (the
    # clip-based first/last q-block computation in _flash_bwd) are
    # otherwise uncovered
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, use_flash=True, block_q=32,
        block_kv=32, window=96) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True, window=96) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=nm)


def test_flash_block_q_off_primitive(devices, pallas_interpret):
    """flash_block_fwd with a static q_off equals the corresponding
    off-diagonal tile of a dense full-sequence attention: q rows sit
    q_off tokens after the block's first key."""
    from deepspeed_tpu.ops.attention.flash import flash_block_fwd
    S_loc, off = 64, 64          # q rows are tokens [64, 128), keys [0, 64)
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(kk, (1, 2 * S_loc, 2, 8), jnp.float32)
               for kk in ks)
    o, lse = flash_block_fwd(q[:, S_loc:], k[:, :S_loc], v[:, :S_loc],
                             causal=True, block_q=32, block_kv=32,
                             window=96, q_off=off)
    # dense tile: full-seq windowed-causal attention restricted to
    # q-rows [64,128) x keys [0,64), renormalized over those keys only
    D = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q[:, S_loc:],
                        k[:, :S_loc]) / np.sqrt(D)
    rows = off + np.arange(S_loc)[:, None]
    cols = np.arange(S_loc)[None, :]
    band = (rows >= cols) & (rows - cols < 96)
    logits = jnp.where(jnp.asarray(band)[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v[:, :S_loc])
    valid = band.any(axis=1)                 # rows inside the band
    np.testing.assert_allclose(np.asarray(o)[0, valid],
                               np.asarray(ref)[0, valid],
                               rtol=2e-5, atol=2e-5)
    # lse is the banded logsumexp for in-band rows: both [H, S] slices
    ref_lse = np.asarray(jax.scipy.special.logsumexp(logits, axis=-1))[0]
    got_lse = np.asarray(lse)[0]
    np.testing.assert_allclose(got_lse[:, valid], ref_lse[:, valid],
                               rtol=2e-5, atol=2e-5)


def test_ring_packed_gpt_matches_ulysses(devices):
    """End-to-end packed batch: ring and Ulysses SP produce the same
    engine loss (both now carry packing metadata; models/gpt.py's SP
    guard is fully lifted)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    from deepspeed_tpu.runtime.dataloader import pack_documents

    r = np.random.default_rng(0)
    docs = [r.integers(0, 128, ln).astype(np.int32)
            for ln in (20, 30, 15, 33, 9, 22)]
    packed = pack_documents(docs, seq_len=65, pad_token=0)
    packed = {k_: v_[:2] for k_, v_ in packed.items()}
    mesh = make_mesh(MeshSpec(data=2, sequence=4))

    def build(impl):
        cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                            d_model=32, max_seq_len=64,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32, sequence_parallel=True,
                            sp_impl=impl, mesh=mesh)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 2,
                    "mesh": {"data_parallel_size": 2,
                             "sequence_parallel_size": 4},
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh)
        return eng

    e_ring = build("ring")
    e_uly = build("ulysses")
    for _ in range(2):
        lr_ = float(e_ring.train_batch(packed)["loss"])
        lu = float(e_uly.train_batch(packed)["loss"])
        np.testing.assert_allclose(lr_, lu, rtol=1e-4)
    assert np.isfinite(lr_)


def test_ring_bf16_matches_dense(devices):
    """The production dtype path: bf16 q/k/v through the ring (fp32
    online-softmax accumulation internally) vs the bf16 dense reference,
    forward and grads at bf16-appropriate tolerances."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = _qkv(B=2, S=64, H=4, D=16, seed=10, dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, mesh, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.05)
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        assert a.dtype == jnp.bfloat16, nm
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0.1, atol=0.1, err_msg=nm)


# ---------------------------------------------------------------------------
# property-based ring invariants (hypothesis)
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:  # environment without hypothesis: collect the
    # rest of the module and skip just the property tests
    import pytest as _pytest

    def given(*a, **k):
        return _pytest.mark.skip(reason="hypothesis not installed")

    def settings(*a, **k):
        return lambda f: f

    class _NoStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _NoStrategies()


@settings(max_examples=15, deadline=None)
@given(
    st.integers(min_value=1, max_value=2),            # batch
    st.sampled_from([32, 64]),                        # seq
    st.sampled_from([(2, 2), (4, 2), (4, 1)]),        # (H, Hkv)
    st.sampled_from([None, 8, 24, 48]),               # window
    st.booleans(),                                    # packed segments?
    st.booleans(),                                    # kv mask?
    st.integers(min_value=0, max_value=10_000),       # seed
)
def test_ring_property_parity(devices, B, S, heads, window, use_segs,
                              use_mask, seed):
    """Randomized geometry sweep: any composition of GQA, packing,
    key-validity masks and sliding windows through the ring must match
    the dense reference on all rows with >=1 visible valid key (the
    documented contract). The ring path re-derives every mask from
    rotated per-token metadata + static step offsets — the exact code
    a geometry off-by-one would live in."""
    H, Hkv = heads
    r = np.random.default_rng(seed)
    q = jnp.asarray(r.standard_normal((B, S, H, 8)), jnp.float32)
    k = jnp.asarray(r.standard_normal((B, S, Hkv, 8)), jnp.float32)
    v = jnp.asarray(r.standard_normal((B, S, Hkv, 8)), jnp.float32)
    segs = None
    if use_segs:
        n_docs = int(r.integers(1, 5))
        bounds = np.sort(r.choice(np.arange(1, S), n_docs - 1,
                                  replace=False)) if n_docs > 1 else []
        ids = np.zeros(S, np.int32)
        for b_ in bounds:
            ids[b_:] += 1
        segs = jnp.asarray(ids[None].repeat(B, 0))
    mask = None
    mask_np = np.ones((B, S), np.float32)
    if use_mask:
        mask_np = (r.random((B, S)) > 0.3).astype(np.float32)
        mask = jnp.asarray(mask_np)

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    out = ring_attention(q, k, v, mesh, causal=True, window=window,
                         segment_ids=segs, kv_mask=mask,
                         chunk=int(r.choice([4, 8, 1024])))
    ref = mha_reference(q, k, v, causal=True, window=window,
                        segment_ids=segs, kv_mask=mask)

    # defined rows: >=1 visible valid key under causal+window+segs+mask
    rows = np.arange(S)[:, None]
    cols = np.arange(S)[None, :]
    vis = rows >= cols
    if window is not None:
        vis &= rows - cols < window
    defined = np.zeros((B, S), bool)
    for b_ in range(B):
        vb = vis & (mask_np[b_][None, :] > 0)
        if segs is not None:
            ids = np.asarray(segs)[b_]
            vb &= ids[:, None] == ids[None, :]
        defined[b_] = vb.any(axis=1)
    np.testing.assert_allclose(np.asarray(out)[defined],
                               np.asarray(ref)[defined],
                               rtol=5e-4, atol=5e-4)


def test_ring_window_masked_impl_matches_dense(devices):
    """window_impl='masked' rides the ring's nondiff window into the
    flash block leafs (tagged tuple), with the early-stop hop count
    still computed from the int — parity with dense must hold."""
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, window=16,
                         window_impl="masked")
    ref = mha_reference(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
