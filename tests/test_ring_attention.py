"""Ring-attention (sequence parallelism) tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.attention.flash import mha_reference
from deepspeed_tpu.ops.attention.ring import ring_attention
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _qkv(B=2, S=64, H=2, D=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(devices, causal):
    q, k, v = _qkv()
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_grads_match_dense(devices):
    q, k, v = _qkv(B=1, S=32, H=2, D=8)
    mesh = make_mesh(MeshSpec(data=1, sequence=8))

    g_ring = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, mesh, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_ring_with_data_parallel_axes(devices):
    """sequence=4 combined with data=2."""
    q, k, v = _qkv(S=32)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sequence_parallel_gpt_trains(devices):
    """GPT with sequence_parallel: loss matches dense-GPT loss and trains."""
    from deepspeed_tpu.models import gpt
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    cfg_dense = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                              d_model=32, max_seq_len=64,
                              use_flash_attention=False, remat=False,
                              dtype=jnp.float32)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 65)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(tokens)},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))

    ds = {"train_batch_size": 8,
          "mesh": {"sequence_parallel_size": 4},
          "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
          "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds,
        mesh=mesh)
    losses = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(8)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[-1] < losses[0] - 0.3
    # divisible token arrays get sequence-sharded (the 65-long shifted input
    # intentionally stays batch-only)
    sharded = engine._shard_batch({"x": tokens[:, :64]})
    assert sharded["x"].sharding.shard_shape((8, 64))[1] == 16


def test_ring_gqa_matches_dense(devices):
    """GQA under ring SP: the small grouped k/v rotate; repeated locally
    per step — matches the dense grouped reference, forward AND grads
    (training with SP + GQA is now allowed)."""
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_r(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_d(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gr, gd, "qkv"):
        assert a.shape == b.shape, n
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=n)


def test_ring_segments_match_dense(devices):
    """Packed segment_ids under the ring: the metadata rotates with its
    K/V block, so block-diagonal masking is exact."""
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(np.repeat(np.arange(4), 16)[None].repeat(2, 0),
                       jnp.int32)
    out = ring_attention(q, k, v, mesh, causal=True, segment_ids=segs)
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_kv_mask_matches_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    r = np.random.default_rng(3)
    mask = jnp.asarray((r.random((2, 64)) > 0.25).astype(np.float32))
    out = ring_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    ref = mha_reference(q, k, v, causal=True, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_window_matches_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16), jnp.float32)
               for kk in ks)
    out = ring_attention(q, k, v, mesh, causal=True, window=16)
    ref = mha_reference(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_packed_grads_match_dense(devices):
    from deepspeed_tpu.ops.attention.flash import mha_reference
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (1, 32, 8, 8), jnp.float32)
               for kk in ks)
    segs = jnp.asarray(np.repeat(np.arange(2), 16)[None], jnp.int32)
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(lambda q, k, v: jnp.sum(mha_reference(
        q, k, v, causal=True, segment_ids=segs) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_r, g_d, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=nm)


def test_ring_packed_gpt_matches_ulysses(devices):
    """End-to-end packed batch: ring and Ulysses SP produce the same
    engine loss (both now carry packing metadata; models/gpt.py's SP
    guard is fully lifted)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    from deepspeed_tpu.runtime.dataloader import pack_documents

    r = np.random.default_rng(0)
    docs = [r.integers(0, 128, ln).astype(np.int32)
            for ln in (20, 30, 15, 33, 9, 22)]
    packed = pack_documents(docs, seq_len=65, pad_token=0)
    packed = {k_: v_[:2] for k_, v_ in packed.items()}
    mesh = make_mesh(MeshSpec(data=2, sequence=4))

    def build(impl):
        cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                            d_model=32, max_seq_len=64,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32, sequence_parallel=True,
                            sp_impl=impl, mesh=mesh)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 2,
                    "mesh": {"data_parallel_size": 2,
                             "sequence_parallel_size": 4},
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh)
        return eng

    e_ring = build("ring")
    e_uly = build("ulysses")
    for _ in range(2):
        lr_ = float(e_ring.train_batch(packed)["loss"])
        lu = float(e_uly.train_batch(packed)["loss"])
        np.testing.assert_allclose(lr_, lu, rtol=1e-4)
    assert np.isfinite(lr_)
