"""Ring-attention (sequence parallelism) tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.attention.flash import mha_reference
from deepspeed_tpu.ops.attention.ring import ring_attention
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _qkv(B=2, S=64, H=2, D=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(devices, causal):
    q, k, v = _qkv()
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_grads_match_dense(devices):
    q, k, v = _qkv(B=1, S=32, H=2, D=8)
    mesh = make_mesh(MeshSpec(data=1, sequence=8))

    g_ring = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, mesh, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_ring_with_data_parallel_axes(devices):
    """sequence=4 combined with data=2."""
    q, k, v = _qkv(S=32)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sequence_parallel_gpt_trains(devices):
    """GPT with sequence_parallel: loss matches dense-GPT loss and trains."""
    from deepspeed_tpu.models import gpt
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    cfg_dense = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                              d_model=32, max_seq_len=64,
                              use_flash_attention=False, remat=False,
                              dtype=jnp.float32)
    tokens = np.random.default_rng(0).integers(0, 128, (8, 65)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(tokens)},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))

    ds = {"train_batch_size": 8,
          "mesh": {"sequence_parallel_size": 4},
          "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
          "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds,
        mesh=mesh)
    losses = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(8)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[-1] < losses[0] - 0.3
    # divisible token arrays get sequence-sharded (the 65-long shifted input
    # intentionally stays batch-only)
    sharded = engine._shard_batch({"x": tokens[:, :64]})
    assert sharded["x"].sharding.shard_shape((8, 64))[1] == 16


def test_ring_gqa_matches_dense(devices):
    """GQA under ring SP: the small grouped k/v rotate; repeated locally
    per step — matches the dense grouped reference, forward AND grads
    (training with SP + GQA is now allowed)."""
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_r(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_d(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gr, gd, "qkv"):
        assert a.shape == b.shape, n
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=n)
