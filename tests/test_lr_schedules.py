"""LR-schedule tests (ref: tests/unit/test_lr_schedulers.py)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRScheduler, constant_lr,
                                                get_lr_schedule, lr_range_test,
                                                one_cycle, warmup_decay_lr,
                                                warmup_lr)


def test_warmup_lr_reaches_max():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.01, warmup_num_steps=10)
    assert float(s(0)) < 0.01
    assert float(s(10)) == pytest.approx(0.01, rel=1e-5)
    assert float(s(100)) == pytest.approx(0.01, rel=1e-5)


def test_warmup_lr_monotone():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.01, warmup_num_steps=50)
    vals = [float(s(i)) for i in range(0, 60, 5)]
    assert all(b >= a - 1e-9 for a, b in zip(vals, vals[1:]))


def test_warmup_decay_goes_to_zero():
    s = warmup_decay_lr(total_num_steps=100, warmup_max_lr=0.01,
                        warmup_num_steps=10)
    assert float(s(100)) == pytest.approx(0.0, abs=1e-6)
    assert float(s(55)) == pytest.approx(0.01 * 0.5, rel=0.1)


def test_lr_range_test_growth():
    s = lr_range_test(min_lr=1e-4, step_rate=1.0, step_size=100, staircase=False)
    assert float(s(0)) == pytest.approx(1e-4)
    assert float(s(100)) == pytest.approx(2e-4)
    stair = lr_range_test(min_lr=1e-4, step_rate=1.0, step_size=100, staircase=True)
    assert float(stair(50)) == pytest.approx(1e-4)


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=0.001, cycle_max_lr=0.01,
                  cycle_first_step_size=100)
    assert float(s(0)) == pytest.approx(0.001, rel=1e-4)
    assert float(s(100)) == pytest.approx(0.01, rel=1e-4)
    assert float(s(200)) == pytest.approx(0.001, rel=1e-3)


def test_get_lr_schedule_dispatch():
    s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.005,
                                     "warmup_num_steps": 10})
    assert float(s(20)) == pytest.approx(0.005, rel=1e-5)
    s2 = get_lr_schedule(None, {}, base_lr=0.1)
    assert float(s2(5)) == pytest.approx(0.1)
    with pytest.raises(ValueError):
        get_lr_schedule("NotASchedule", {})


def test_stateful_wrapper():
    sched = LRScheduler(constant_lr(0.5))
    sched.step()
    assert sched.get_lr() == [0.5]
    sd = sched.state_dict()
    sched2 = LRScheduler(constant_lr(0.5))
    sched2.load_state_dict(sd)
    assert sched2.last_batch_iteration == sched.last_batch_iteration
