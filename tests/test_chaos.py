"""Chaos suite: the serving + checkpoint robustness contract driven by
the deterministic fault injector (tentpole: utils/faults.py + the
graceful-degradation paths in inference/serving.py).

Layers:
  1. injector unit tests — spec grammar, visit scheduling, the fired
     log, seeded-jitter determinism, ambient install/restore;
  2. serving under chaos — injected cache exhaustion, transient device
     errors and slow steps with a FIXED seed: every non-shed request
     must finish exactly once with token parity against the fault-free
     greedy stream (the acceptance gate), expired requests end
     ``state="timeout"``, full queues shed, the watchdog raises a
     structured DegradedError that loses nothing, retry exhaustion
     propagates, and the eviction-storm guard truncates instead of
     livelocking;
  3. the compile-count contract under chaos — deadlines, shedding,
     backoff and injected faults are host-side only, so the steady
     state stays at two compiled programs with ZERO recompiles.

Crash-mid-checkpoint scenarios live with the other checkpoint tests in
tests/test_checkpointing.py (same injector, ``checkpoint.*`` sites).
"""

import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine)
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import (Fault, FaultInjector, InjectedCrash,
                                        TransientDeviceError,
                                        UnknownFaultSiteWarning, parse_spec)


# ---------------------------------------------------------------------------
# injector unit tests (pure host — no devices needed)
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    fs = parse_spec("serving.decode:device_error@3;"
                    "cache.ensure:cache_exhausted@5*2~0.5")
    assert fs[0] == Fault("serving.decode", "device_error", step=3)
    assert fs[1] == Fault("cache.ensure", "cache_exhausted", step=5,
                          count=2, param=0.5)
    # ',' is accepted as a ';' synonym; blank entries are skipped
    assert parse_spec("a.b:slow@0~0.1, c.d:crash@2") == [
        Fault("a.b", "slow", param=0.1), Fault("c.d", "crash", step=2)]
    assert parse_spec("") == []
    with pytest.raises(ValueError, match="bad fault spec"):
        parse_spec("no-colon-here")
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_spec("site:meteor_strike@0")


def test_injector_visit_schedule_and_fired_log():
    inj = FaultInjector([Fault("s", "slow", step=1, count=2, param=0.0)])
    assert inj.visit("s") is None                 # visit 0: before window
    assert inj.visit("s").kind == "slow"          # visits 1, 2: inside
    assert inj.visit("s") is not None
    assert inj.visit("s") is None                 # visit 3: past it
    assert inj.visit("other") is None             # sites are independent
    assert inj.fired == [("s", "slow", 1), ("s", "slow", 2)]
    inj.reset()                                   # same timeline replays
    assert inj.visit("s") is None and inj.fired == []


def test_injector_fire_raises_generic_kinds():
    inj = FaultInjector([Fault("a", "device_error"), Fault("b", "crash"),
                         Fault("c", "cache_exhausted")])
    with pytest.raises(TransientDeviceError):
        inj.fire("a")
    with pytest.raises(InjectedCrash):
        inj.fire("b")
    # domain-specific kinds are RETURNED for the site to interpret
    f = inj.fire("c")
    assert f is not None and f.kind == "cache_exhausted"
    assert inj.fire("c") is None                  # one-shot by default


def test_jitter_is_seed_deterministic():
    a, b = FaultInjector(seed=42), FaultInjector(seed=42)
    seq = [a.jitter(1.0) for _ in range(4)]
    assert seq == [b.jitter(1.0) for _ in range(4)]
    assert all(0.0 <= j < 1.0 for j in seq)
    assert seq != [FaultInjector(seed=43).jitter(1.0) for _ in range(4)]


def test_injector_from_env_mapping():
    inj = FaultInjector.from_env({"DS_FAULTS": "x.y:crash@2",
                                  "DS_FAULT_SEED": "7"})
    assert inj.faults == [Fault("x.y", "crash", step=2)] and inj.seed == 7
    assert FaultInjector.from_env({}).faults == []


def test_injected_context_installs_and_restores():
    base = faults_lib.active()
    with faults_lib.injected(Fault("q", "slow"), seed=5) as inj:
        assert faults_lib.active() is inj and inj.seed == 5
    assert faults_lib.active() is base


# ---------------------------------------------------------------------------
# serving under chaos
# ---------------------------------------------------------------------------

def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


@pytest.fixture(scope="module")
def eng(devices):
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


def test_chaos_parity_under_injected_faults(eng):
    """The acceptance gate: injected cache exhaustion + transient device
    errors (serving AND engine level) + a slow step, all scheduled by
    one seeded injector — every request still finishes exactly once,
    token-for-token equal to the fault-free greedy stream."""
    prompts = prompts_of((5, 9, 12, 3))
    refs = _solo_refs(eng, prompts, 6)
    chaos = [Fault("serving.prefill", "device_error", step=1),
             Fault("serving.decode", "device_error", step=2),
             Fault("engine.decode", "device_error", step=4),
             Fault("serving.decode", "slow", step=6, param=0.005),
             Fault("cache.ensure", "cache_exhausted", step=5)]
    with faults_lib.injected(*chaos, seed=0) as inj:
        # spec and the decode horizon pinned off here and below: these
        # tests exercise the PLAIN decode path's fault sites
        # (serving.decode fires per one-token dispatch, and the injected
        # visit indices are calibrated to that cadence); the speculative
        # sites' chaos contract is test_spec_serving.py's job, the
        # serving.horizon degrade is test_horizon.py's
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, max_retries=3,
                            retry_backoff_s=0.001, spec_decode=False,
                            decode_horizon=1)
        out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)
                       for i, p in enumerate(prompts)])
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    # exactly-once: four terminal requests, all "done", no duplicates
    assert sorted(r.rid for r in srv.finished) == [0, 1, 2, 3]
    assert all(r.state == "done" for r in srv.finished)
    # the chaos really happened and was survived
    assert srv.stats["retries"] >= 3
    assert srv.stats["evictions"] >= 1          # injected exhaustion evicted
    kinds = {k for _s, k, _v in inj.fired}
    assert {"device_error", "cache_exhausted", "slow"} <= kinds


def test_deadline_expires_slot_holder_with_partial_tokens(eng):
    """A slot holder past its deadline retires as ``timeout`` keeping
    its partial output (a prefix of the fault-free stream) and frees
    its blocks; unaffected requests keep full parity."""
    p1, p2 = prompts_of((6, 7), seed=5)
    ref1 = _solo_refs(eng, [p1], 30)[0]
    ref2 = _solo_refs(eng, [p2], 8)[0]
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24)
    out = srv.run([ServeRequest(rid="t", prompt=p1, max_new_tokens=30,
                                deadline=4.0),     # scheduler-step clock
                   ServeRequest(rid="ok", prompt=p2, max_new_tokens=8)])
    done = {r.rid: r for r in srv.finished}
    assert done["t"].state == "timeout"
    assert 0 < len(done["t"].out) < 30
    np.testing.assert_array_equal(
        out["t"], ref1[:len(p1) + len(done["t"].out)])
    np.testing.assert_array_equal(out["ok"], ref2)
    assert srv.stats["timeouts"] == 1
    assert not srv.cache.active.any()            # timed-out blocks freed


def test_deadline_expires_queued_request_without_a_slot(eng):
    """A queued request whose deadline passes before admission times out
    in place — it never claims a slot or blocks."""
    p1, p2 = prompts_of((8, 8), seed=6)
    srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=24)
    out = srv.run([ServeRequest(rid="long", prompt=p1, max_new_tokens=20),
                   ServeRequest(rid="q", prompt=p2, max_new_tokens=4,
                                deadline=2.0)])
    done = {r.rid: r for r in srv.finished}
    assert done["q"].state == "timeout" and done["q"].out == []
    np.testing.assert_array_equal(out["q"], p2)  # prompt only
    assert done["long"].state == "done"


def test_bounded_queue_sheds_newest(eng):
    """reject-newest load shedding: the submit into a full queue gets an
    immediate terminal answer (``shed``) and backpressure reads 1.0;
    accepted work is untouched."""
    prompts = prompts_of((5, 6, 7), seed=8)
    refs = _solo_refs(eng, prompts[:2], 4)
    srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=24,
                        max_queue=2)
    reqs = [ServeRequest(rid=i, prompt=p, max_new_tokens=4)
            for i, p in enumerate(prompts)]
    assert srv.submit(reqs[0]) and srv.submit(reqs[1])
    assert srv.stats["backpressure"] == 1.0      # queue at capacity
    assert not srv.submit(reqs[2])               # shed, not queued
    assert reqs[2].state == "shed" and srv.stats["shed"] == 1
    out = srv.run()
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    np.testing.assert_array_equal(out[2], prompts[2])   # no tokens
    assert srv.stats["backpressure"] == 0.0      # drained
    # exactly one terminal state per submitted request
    assert sorted(r.rid for r in srv.finished) == [0, 1, 2]


def test_watchdog_degraded_error_keeps_everything(eng):
    """Consecutive over-budget decode steps (a hung step is a ``slow``
    fault bigger than the budget) raise DegradedError with every
    finished result AND an in-flight snapshot attached; the scheduler
    state stays consistent, so continuing to step drains to full
    parity."""
    p1, p2 = prompts_of((6, 9), seed=12)
    ref1 = _solo_refs(eng, [p1], 12)[0]
    ref2 = _solo_refs(eng, [p2], 3)[0]
    with faults_lib.injected(
            Fault("serving.decode", "slow", step=4, count=2, param=0.05)):
        # 10ms budget: well above a normal decode dispatch (which now
        # includes the fused in-program sampler), well below the 50ms
        # injected slow fault — same calibration as the drain tests
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            step_time_budget_s=0.01, watchdog_grace=2,
                            spec_decode=False, decode_horizon=1)
        with pytest.raises(DegradedError, match="over budget") as ei:
            srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                     ServeRequest(rid="b", prompt=p2, max_new_tokens=3)])
        e = ei.value
        # "b" finished before the trip; "a" is mid-flight with its
        # tokens intact in the snapshot — nothing thrown away
        np.testing.assert_array_equal(e.results["b"], ref2)
        assert [p["rid"] for p in e.pending] == ["a"]
        assert e.pending[0]["generated"] > 0
        assert e.stats["watchdog_trips"] >= 2
        out = srv.run()                          # resume: drains cleanly
    np.testing.assert_array_equal(out["a"], ref1)
    assert all(r.state == "done" for r in srv.finished)


def test_retry_backoff_survives_transient_burst(eng):
    """A burst shorter than max_retries is absorbed: the request
    completes with parity and the retries are counted."""
    p = prompts_of((7,), seed=14)[0]
    ref = _solo_refs(eng, [p], 5)[0]
    with faults_lib.injected(
            Fault("serving.decode", "device_error", step=1, count=2)):
        srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=24,
                            max_retries=3, retry_backoff_s=0.001,
                            spec_decode=False, decode_horizon=1)
        out = srv.run([ServeRequest(rid=0, prompt=p, max_new_tokens=5)])
    np.testing.assert_array_equal(out[0], ref)
    assert srv.stats["retries"] == 2


def test_retry_exhaustion_propagates(eng):
    """A fault outlasting the retry budget surfaces as
    TransientDeviceError — the engine does not spin forever."""
    p = prompts_of((6,), seed=15)[0]
    with faults_lib.injected(
            Fault("serving.decode", "device_error", step=0, count=10)):
        srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=24,
                            max_retries=2, retry_backoff_s=0.001,
                            spec_decode=False)
        with pytest.raises(TransientDeviceError):
            srv.run([ServeRequest(rid=0, prompt=p, max_new_tokens=5)])
    assert srv.stats["retries"] == 2


def test_eviction_cap_truncates_instead_of_livelock(eng):
    """With every request pinned (max_evictions=0) and a pool that
    cannot grow, the engine truncate-finishes rather than thrashing:
    it drains, outputs are prefixes of the fault-free streams, and the
    guard is visible in ``evict_capped``."""
    p1, p2 = prompts_of((10, 9), seed=9)
    refs = {"a": _solo_refs(eng, [p1], 12)[0],
            "b": _solo_refs(eng, [p2], 10)[0]}
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                        max_evictions=0)
    srv.cache.watermark = 0
    out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                   ServeRequest(rid="b", prompt=p2, max_new_tokens=10)],
                  max_steps=500)
    assert srv.stats["evictions"] == 0           # nobody was preempted
    assert srv.stats["evict_capped"] >= 1
    for rid, req in ((r.rid, r) for r in srv.finished):
        assert req.state == "done"
        np.testing.assert_array_equal(
            out[rid], refs[rid][:len(out[rid])])  # truncated, not wrong


def test_chaos_compile_count_contract(eng):
    """The robustness features are host-side only: with deadlines,
    shedding, a watchdog budget, backoff AND injected faults all
    active, the steady state is still exactly two compiled programs
    and ZERO recompiles."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    p1, p2 = prompts_of((10, 9), seed=9)

    def run_workload(chaos):
        with faults_lib.injected(*chaos, seed=0):
            srv = ServingEngine(eng, num_slots=2, block_size=4,
                                num_blocks=7, prefill_chunk=8,
                                max_queue=4, max_retries=3,
                                retry_backoff_s=0.001,
                                step_time_budget_s=10.0,
                                spec_decode=False, decode_horizon=1)
            srv.cache.watermark = 0
            out = srv.run(
                [ServeRequest(rid="a", prompt=p1, max_new_tokens=12,
                              deadline=1e9),
                 ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return srv, out

    srv, warm = run_workload([])                 # warmup compiles all
    assert srv.stats["evictions"] >= 1
    # the module-shared engine carries one program per pool shape the
    # earlier tests used; the contract here is that chaos adds NONE
    n_before = (cache_size(eng._prefill_slot), cache_size(eng._decode_slots))
    chaos = [Fault("serving.prefill", "device_error", step=1),
             Fault("serving.decode", "device_error", step=3),
             Fault("cache.ensure", "cache_exhausted", step=4)]
    watch = CompileWatch(max_compiles=0, label="chaos steady state")
    watch.wrap(eng._prefill_slot)
    watch.wrap(eng._decode_slots)
    with watch:                                  # raises on any compile
        srv2, out = run_workload(chaos)
    assert srv2.stats["retries"] >= 2
    for rid in ("a", "b"):
        np.testing.assert_array_equal(out[rid], warm[rid])
    if n_before[0] is not None:
        assert (cache_size(eng._prefill_slot),
                cache_size(eng._decode_slots)) == n_before


def test_chaos_prefix_cache_sites_parity(eng):
    """The prefix-cache fault sites under one seeded injector: a
    ``cache.match`` exhaustion degrades an admission to a cold miss, a
    ``cache.cow`` exhaustion aborts a copy-on-write admission BEFORE
    any bookkeeping (the request retries and succeeds), and a transient
    device error rides along — parity and exactly-once still hold with
    the prefix cache on."""
    base = np.arange(1, 31, dtype=np.int32)          # 30 tokens, bs=8
    div = base.copy()
    div[21] = 99                                     # diverges mid-block
    refs = _solo_refs(eng, [base, base, div], 6)
    chaos = [Fault("cache.match", "cache_exhausted", step=1),
             Fault("cache.cow", "cache_exhausted", step=0),
             Fault("serving.decode", "device_error", step=2)]
    with faults_lib.injected(*chaos, seed=0) as inj:
        srv = ServingEngine(eng, num_slots=1, block_size=8, num_blocks=24,
                            prefill_chunk=16, prefix_cache=True,
                            max_retries=3, retry_backoff_s=0.001,
                            spec_decode=False)
        out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)
                       for i, p in enumerate((base, base, div))])
    fired_sites = {s for s, _k, _v in inj.fired}
    assert {"cache.match", "cache.cow", "serving.decode"} <= fired_sites
    # request 0 cold; request 1's lookup was degraded to a miss (visit
    # 1); request 2's first COW attempt failed and the retry landed
    assert srv.stats["prefix_hits"] == 1
    assert srv.cache.cow_copies == 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert all(r.state == "done" for r in srv.finished)
    assert srv.cache.held_blocks == 0
    assert (srv.cache._refcount == 0).all()          # no leaked claims


def test_parse_spec_warns_once_on_unknown_site():
    """A typo'd site warns loudly (once per site) instead of silently
    injecting nothing; known sites parse quietly."""
    faults_lib._warned_sites.discard("serving.prefil")
    with pytest.warns(UnknownFaultSiteWarning, match="serving.prefil"):
        parse_spec("serving.prefil:crash@0")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        parse_spec("serving.prefil:crash@0")     # already warned: silent
        parse_spec("serving.decode:crash@0")     # known site: silent


def test_retry_backoff_capped_by_slot_deadline(eng):
    """Backoff sleeps never outlive the tightest active-slot deadline:
    with retry_backoff_s=5.0 an uncapped burst of 3 retries would
    sleep >= 1.5 s (each pause floors at the 0.5 s clamp); the slack
    cap bounds the whole wall-clock run by the request's deadline and
    retires it as a timeout with its partial tokens."""
    pw, p = prompts_of((6, 7), seed=41)
    with faults_lib.injected(
            Fault("serving.decode", "device_error", step=8, count=3),
            seed=0) as inj:
        srv = ServingEngine(eng, num_slots=1, block_size=4, num_blocks=16,
                            prefill_chunk=8, max_retries=3,
                            retry_backoff_s=5.0, spec_decode=False,
                            decode_horizon=1)
        # warmup run (decode visits 0-3): compiles this pool shape so
        # the timed request's deadline measures backoff, not XLA
        srv.run([ServeRequest(rid="w", prompt=pw, max_new_tokens=4)],
                wall_clock=True)
        t0 = time.perf_counter()
        req = ServeRequest(rid="d", prompt=p, max_new_tokens=32,
                           deadline=t0 + 0.3)
        srv.run([req], wall_clock=True)
        elapsed = time.perf_counter() - t0
    assert inj.fired and srv.stats["retries"] >= 1
    assert elapsed < 1.0, f"backoff ignored the slot deadline: {elapsed:.2f}s"
    assert req.state == "timeout" and len(req.out) >= 1


def test_pending_snapshot_cold_resumes_into_fresh_engine(eng):
    """The degrade snapshot is cold-resume complete: feeding its
    entries (via ServeRequest.from_snapshot) to a FRESH engine finishes
    every request token-identical to an undisturbed run, and
    pending_snapshot(release=True) frees the dead engine's cache
    claims so its pool is reclaimable."""
    prompts = prompts_of((6, 9, 12), seed=43)
    refs = _solo_refs(eng, prompts, 8)
    with faults_lib.injected(
            Fault("serving.decode", "slow", step=3, param=0.05), seed=0):
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            prefill_chunk=8, step_time_budget_s=0.01,
                            watchdog_grace=1, spec_decode=False,
                            decode_horizon=1)
        with pytest.raises(DegradedError) as ei:
            srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=8)
                     for i, p in enumerate(prompts)])
    e = ei.value
    snap = srv.pending_snapshot(release=True)
    assert {s["rid"] for s in snap} == {s["rid"] for s in e.pending}
    assert srv.cache.held_blocks == 0 and not srv.queue
    assert (srv.cache._refcount == 0).all()
    fresh = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                          prefill_chunk=8, spec_decode=False)
    out = fresh.run([ServeRequest.from_snapshot(s) for s in snap])
    out.update(e.results)
    assert set(out) == set(range(len(prompts)))
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)
    assert all(r.state == "done" for r in fresh.finished)
