"""Speculative decoding inside continuous batching: per-slot
draft/verify over the paged KV cache (tentpole: inference/spec_decode.py
+ ServingEngine._spec_decode_step + InferenceEngine.verify_slots +
PagedKVCache.rollback; docs/SPECULATIVE.md).

The contract under test: with greedy-target-equality acceptance,
spec-on serving is TOKEN-BIT-IDENTICAL to spec-off greedy serving under
every scheduler behavior (staggered arrivals, eviction/requeue, prefix
cache hits, injected faults) — speculation changes how many verify
steps the same tokens take, never the tokens. Plus the rollback
invariant (a rejected draft chunk straddling a block edge releases the
tail block), the compile contract (ONE verify program replaces the
plain decode program; zero steady-state recompiles), and the chaos
degrade path (a draft/verify fault falls back to plain one-token
decode for that step)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.paged_cache import PagedKVCache
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.inference.spec_decode import (NGramDraft, make_draft,
                                                 resolve_spec_decode,
                                                 resolve_spec_k)
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def serve(eng, prompts, n_new=10, spec=True, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 24)
    kw.setdefault("prefill_chunk", 8)
    srv = ServingEngine(eng, spec_decode=spec, **kw)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=n_new)
                   for i, p in enumerate(prompts)])
    return out, srv


# ---------------------------------------------------------------------------
# drafter + knob units
# ---------------------------------------------------------------------------

def test_ngram_draft_proposals():
    """Prompt-lookup drafting: the trailing n-gram's most recent earlier
    occurrence supplies the continuation; no match repeats the last
    token; the proposal is ALWAYS exactly (k,) int32 (static verify
    shape)."""
    d = NGramDraft(max_ngram=3)
    # trailing [1,2,3] matched at position 0 -> continuation [4,1,2]
    np.testing.assert_array_equal(
        d.propose([1, 2, 3, 4, 1, 2, 3], 3), [4, 1, 2])
    # continuation shorter than k: pad by repeating its last token
    np.testing.assert_array_equal(
        d.propose([7, 8, 9, 7, 8], 4), [9, 7, 8, 8])
    # no repetition anywhere: fall back to repeating the last token
    np.testing.assert_array_equal(d.propose([5], 3), [5, 5, 5])
    for ctx in ([], [3], [1, 2, 3, 1, 2]):
        p = d.propose(ctx, 5)
        assert p.shape == (5,) and p.dtype == np.int32


def test_spec_knob_resolution(monkeypatch):
    monkeypatch.delenv("DS_SPEC_DECODE", raising=False)
    assert resolve_spec_decode(None) is False      # default: off
    assert resolve_spec_decode(True) is True
    monkeypatch.setenv("DS_SPEC_DECODE", "on")
    assert resolve_spec_decode(None) is True
    assert resolve_spec_decode(False) is False     # explicit beats env
    monkeypatch.setenv("DS_SPEC_DECODE", "sideways")
    with pytest.raises(ValueError, match="DS_SPEC_DECODE"):
        resolve_spec_decode(None)
    monkeypatch.setenv("DS_SPEC_K", "6")
    assert resolve_spec_k(None) == 6
    with pytest.raises(ValueError, match="spec_k"):
        resolve_spec_k(0)
    assert isinstance(make_draft("ngram"), NGramDraft)
    with pytest.raises(ValueError, match="spec_draft"):
        make_draft(object())


def test_spec_accepts_sampled_requests(devices):
    """The historical greedy-only guard is gone: spec decode with
    temperature>0 constructs and drains via rejection-sampling verify,
    and the same config at the same seed is deterministic (the verify
    uniforms are counter-based Philox(seed, position) — no sequential
    state to drift)."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    runs = []
    for _ in range(2):
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=24,
                            spec_decode=True, spec_k=3, temperature=0.7)
        runs.append(srv.run([
            ServeRequest(rid=0, prompt=p, max_new_tokens=6, seed=11)
            for i, p in enumerate(prompts_of((9,)))]))
        assert srv.stats["spec_steps"] > 0
    assert np.array_equal(runs[0][0], runs[1][0])


# ---------------------------------------------------------------------------
# rollback hardening (satellite: paged_cache.rollback)
# ---------------------------------------------------------------------------

def test_rollback_releases_straddling_tail_block(devices):
    """A fully-rejected draft chunk that straddled a block edge must
    return the tail block to the pool: lengths shrink AND the block
    table entry clears (a leaked entry would pin one pool block per
    reject for the request's lifetime)."""
    cfg, _ = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=8)
    c.allocate(0, 6)
    c.advance(0, 6)                      # committed length 6, 2 blocks
    # verify chunk of 5 tokens wants positions 6..10 -> a third block
    c.ensure_capacity(0, 11)
    assert c.stats()["used_blocks"] == 3
    # full reject: only the pending token commits (6 -> 7); the draft
    # suffix straddled into block 3, which only rejects were using
    c.advance(0, 1)
    c.rollback(0, 7)
    assert int(c.lengths[0]) == 7
    assert c.stats()["used_blocks"] == 2
    assert c.tables[0, 2] == 0           # table entry cleared, not leaked
    assert c.free_blocks == 6
    # partial accept inside the kept block: lengths move, blocks don't
    c.ensure_capacity(0, 12)
    c.advance(0, 2)
    c.rollback(0, 8)                     # 8 tokens == exactly 2 blocks
    assert c.stats()["used_blocks"] == 2 and int(c.lengths[0]) == 8


def test_rollback_rejects_bad_targets(devices):
    cfg, _ = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=8)
    c.allocate(0, 5)
    c.advance(0, 5)
    with pytest.raises(ValueError, match="outside the allocated"):
        c.rollback(0, 9)                 # beyond capacity: growing is
    with pytest.raises(ValueError, match="outside the allocated"):
        c.rollback(0, -1)                # advance's job, not rollback's
    with pytest.raises(ValueError, match="not active"):
        c.rollback(1, 0)
    # legal rollbacks at the boundaries
    c.rollback(0, int(c.lengths[0]))     # no-op
    assert int(c.lengths[0]) == 5 and c.stats()["used_blocks"] == 2


# ---------------------------------------------------------------------------
# token parity: spec-on == spec-off, everywhere
# ---------------------------------------------------------------------------

def test_spec_serving_greedy_parity(devices):
    """Spec-on greedy serving is token-bit-identical to spec-off, and
    actually speculates (fewer verify dispatches than tokens, multi-
    token steps observed)."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 9, 12, 3))
    off, _ = serve(eng, prompts, spec=False)
    on, srv = serve(eng, prompts, spec=True)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])
    st = srv.stats
    assert st["spec_steps"] > 0 and st["completed"] == len(prompts)
    # speculation paid off: more tokens out than per-slot verify steps
    assert st["spec_emitted"] > st["spec_slot_steps"]
    assert st["spec_accepted"] > 0


def test_spec_serving_parity_rotary_gqa_window(devices):
    """The verify program composes with rotary positions, grouped KV
    heads and sliding-window masking — same stack the decode kernel
    already covers."""
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, rotary_dim=4, use_wpe=False,
                              n_kv_heads=2, attn_window=6)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((4, 10, 7), seed=7)
    off, _ = serve(eng, prompts, n_new=8, spec=False, num_slots=3,
                   num_blocks=30)
    on, _ = serve(eng, prompts, n_new=8, spec=True, num_slots=3,
                  num_blocks=30)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])


def test_spec_serving_parity_pallas(devices):
    """Parity holds through the pallas verify kernel (interpret mode on
    CPU): the q_len>1 grid dimension scores the same chunk the gather
    reference does."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 11), seed=3)
    off, _ = serve(eng, prompts, spec=False, decode_impl="pallas")
    on, _ = serve(eng, prompts, spec=True, decode_impl="pallas")
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])


def test_spec_serving_parity_under_eviction(devices):
    """Eviction/requeue composes with speculation: a preempted slot
    re-prefills prompt+generated and resumes speculating, streams stay
    identical to spec-off under the same pool pressure."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 9, 12, 3))
    off, s0 = serve(eng, prompts, spec=False, num_blocks=7)
    on, s1 = serve(eng, prompts, spec=True, num_blocks=7)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])
    assert s1.stats["evictions"] >= 1    # the pressure really preempted
    assert s1.stats["completed"] == len(prompts)


def test_spec_serving_parity_prefix_cache(devices):
    """Prefix-cache hits compose with speculation: shared prompt blocks
    map read-only into speculating slots and the verify chunk writes
    past them; streams match spec-off with the cache on."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    sys_p = (1 + np.arange(12) % 126).astype(np.int32)
    tails = prompts_of((4, 7, 5), seed=11)
    prompts = [np.concatenate([sys_p, t]) for t in tails]
    off, _ = serve(eng, prompts, spec=False, prefix_cache=True)
    on, srv = serve(eng, prompts, spec=True, prefix_cache=True)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])
    assert srv.stats["prefix_hits"] >= 1  # sharing really happened


# ---------------------------------------------------------------------------
# per-slot independence
# ---------------------------------------------------------------------------

class _HalfOracle:
    """Drafter with per-request quality: perfect continuations (read
    from precomputed reference streams) for requests it knows, garbage
    for the rest — so two slots in the SAME verify dispatch accept
    different prefix lengths."""

    def __init__(self, refs, vocab):
        self.refs = [np.asarray(r) for r in refs]
        self.vocab = vocab

    def propose(self, context, k):
        ctx = np.asarray(context)
        for ref in self.refs:
            if ctx.size <= ref.size and \
                    np.array_equal(ref[:ctx.size], ctx):
                cont = ref[ctx.size:ctx.size + k]
                out = np.full((k,), self.vocab - 1, np.int64)
                out[:cont.size] = cont
                return out.astype(np.int32)
        return np.full((k,), self.vocab - 1, np.int32)


def test_spec_per_slot_divergent_acceptance(devices):
    """Acceptance is per-slot, not batch-lockstep (the static
    generate_speculative takes the batch min): with an oracle drafter
    for request 0 and garbage for request 1, one verify step must
    accept >0 for slot A and 0 for slot B — and both streams still
    match spec-off."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((6, 6), seed=5)
    off, _ = serve(eng, prompts, spec=False)
    oracle = _HalfOracle([np.concatenate([prompts[0], off[0][6:]])],
                         cfg.vocab_size)
    on, srv = serve(eng, prompts, spec=True, spec_draft=oracle,
                    telemetry=True)
    for i in range(2):
        np.testing.assert_array_equal(off[i], on[i])
    # tracer records: (ts, etype, rid, step, slot, data)
    accepted = [r[5]["accepted"]
                for r in srv.telemetry.tracer.records()
                if r[1] == "spec_verify"]
    assert accepted, "no spec_verify events traced"
    divergent = [a for a in accepted
                 if len(a) == 2 and max(a.values()) > 0
                 and min(a.values()) == 0]
    assert divergent, (
        f"no step accepted differently across slots: {accepted}")


# ---------------------------------------------------------------------------
# compile contract
# ---------------------------------------------------------------------------

def test_spec_compile_count_contract(devices):
    """With speculation on, the verify program REPLACES plain decode:
    steady state is prefill=1 + verify=1 compiled programs, decode=0,
    and a second workload (including eviction/requeue) compiles
    NOTHING."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    p1, p2 = prompts_of((10, 9), seed=9)

    def run_workload():
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                            prefill_chunk=8, spec_decode=True)
        srv.cache.watermark = 0
        out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                       ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return srv, out

    srv, warm_out = run_workload()
    assert srv.stats["evictions"] >= 1   # the workload really preempts
    # under DS_KV_QUANT=int8 / DS_LORA_SERVE=on the active set is the
    # _q / _l / _ql jit twin family; the verify-replaces-decode count
    # contract is identical in every mode
    sfx = ("_q" if srv.kv_quant == "int8" else "") + \
          ("_l" if srv.lora_serve else "")
    pf = getattr(eng, "_prefill_slot" + sfx)
    vf = getattr(eng, "_verify_slots" + sfx)
    dc = getattr(eng, "_decode_slots" + sfx)
    n_prefill = cache_size(pf)
    n_verify = cache_size(vf)
    n_decode = cache_size(dc)
    if n_prefill is not None:
        assert (n_prefill, n_verify, n_decode) == (1, 1, 0), (
            f"spec steady state fragmented: prefill={n_prefill} "
            f"verify={n_verify} decode={n_decode} (expected 1+1+0: "
            f"verify replaces decode)")

    watch = CompileWatch(max_compiles=0, label="spec serving steady state")
    watch.wrap(pf)
    watch.wrap(vf)
    watch.wrap(dc)
    with watch:
        srv2, out = run_workload()
    assert srv2.stats["evictions"] >= 1
    for rid in ("a", "b"):
        np.testing.assert_array_equal(out[rid], warm_out[rid])


# ---------------------------------------------------------------------------
# chaos: degrade to plain decode, never to wrong output
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["engine.verify", "serving.spec_draft"])
def test_spec_chaos_degrades_to_plain_decode(devices, site):
    """An injected fault at either speculative site downgrades THAT
    step to the plain one-token path (spec_fallbacks counts it); the
    run still drains and streams stay bit-identical to the clean
    spec-off run."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 9, 12, 3))
    off, _ = serve(eng, prompts, spec=False)
    with faults.injected(faults.Fault(site, "device_error",
                                      step=1, count=3)):
        on, srv = serve(eng, prompts, spec=True)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(off[i], on[i])
    assert srv.stats["spec_fallbacks"] >= 3
    # the degraded steps really ran the plain program
    assert srv.stats["decode_steps"] > srv.stats["spec_steps"]
    assert srv.stats["completed"] == len(prompts)


# ---------------------------------------------------------------------------
# telemetry (satellite: accept_rate / tokens_per_step observability)
# ---------------------------------------------------------------------------

def test_spec_telemetry_metrics_and_trace(devices):
    """With telemetry on, speculative steps feed the accept-rate and
    tokens-per-step histograms and trace one spec_verify event per
    dispatch with the per-slot accepted counts."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    prompts = prompts_of((5, 9), seed=2)
    _, srv = serve(eng, prompts, spec=True, telemetry=True)
    st = srv.stats
    h_acc = srv.metrics.histogram("serving_spec_accept_rate")
    h_tps = srv.metrics.histogram("serving_spec_tokens_per_step")
    assert h_acc.count == st["spec_steps"] > 0
    assert h_tps.count == st["spec_steps"]
    # tokens/step mean > 1: speculation emitted multi-token steps
    assert h_tps.sum / h_tps.count > 1.0
    events = [r[5] for r in srv.telemetry.tracer.records()
              if r[1] == "spec_verify"]
    assert len(events) == st["spec_steps"]
    assert all("accepted" in d and "emitted" in d for d in events)
    assert sum(d["emitted"] for d in events) == st["spec_emitted"]
    # the exposition includes the new families
    prom = srv.telemetry.to_prometheus()
    assert "serving_spec_accept_rate_bucket" in prom
    assert "serving_spec_tokens_per_step_sum" in prom
