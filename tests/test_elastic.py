"""Elasticity tests (ref: tests/unit/test_elastic.py:270 — candidate
batch math, invalid-world, config validation)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import (
    ElasticityConfig, ElasticityConfigError, ElasticityError,
    ElasticityIncompatibleWorldSize, compute_elastic_config,
    elasticity_enabled)
from deepspeed_tpu.version import __version__
from tests.simple_model import random_batch, simple_model_loss, simple_model_params

BASE_CONFIG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_basic_10k():
    """Reference fixture: 10k cap, micro [8,12,16,17] → every valid chip
    count divides the final batch by some micro batch
    (ref: test_elastic.py test_basic_10k, expected value :41)."""
    final_batch_size, valid_gpus = compute_elastic_config(
        BASE_CONFIG, target_deepspeed_version=__version__)
    assert final_batch_size == 9792  # exact reference-algorithm parity
    for gpu_num in valid_gpus:
        assert final_batch_size % gpu_num == 0
        micros = final_batch_size // gpu_num
        assert any(micros % mb == 0
                   for mb in BASE_CONFIG["elasticity"]["micro_batch_sizes"])
    assert all(32 <= g <= 1500 for g in valid_gpus)
    assert final_batch_size <= 10000


def test_candidate_world_sizes():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2, 4, 6], "version": 0.1}}
    final, valid = compute_elastic_config(cfg, __version__)
    # 2000-cap/[2,4,6]: LCM-HCN heuristic lands on 1680 = 2 * 840
    assert final == 1680
    assert 1 in valid and 2 in valid and 4 in valid


def test_invalid_world_size_rejected():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2, 4, 6], "version": 0.1}}
    final, valid = compute_elastic_config(cfg, __version__)
    bad = max(valid) + 1
    while bad in valid:
        bad += 1
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, __version__, world_size=bad)


def test_world_size_micro_batch():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2, 4, 6], "version": 0.1}}
    final, valid, micro = compute_elastic_config(cfg, __version__,
                                                 world_size=4)
    assert micro in (2, 4, 6)
    assert (final // 4) % micro == 0


def test_allowed_chip_counts_filter():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2, 4, 6], "version": 0.1}}
    _, valid = compute_elastic_config(
        cfg, __version__, allowed_chip_counts={1, 4, 8, 16, 32, 64, 128})
    assert valid and all(v in {1, 4, 8, 16, 32, 64, 128} for v in valid)


def test_disabled_and_missing_raise():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({}, __version__)
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(
            {"elasticity": {"enabled": False}}, __version__)
    assert not elasticity_enabled({})


def test_config_validation():
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "max_train_batch_size": 100})
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "max_train_batch_size": 100,
                          "micro_batch_sizes": "4"})
    with pytest.raises(ElasticityConfigError):
        ElasticityConfig({"enabled": True, "max_train_batch_size": 100,
                          "micro_batch_sizes": [0, 4]})
    with pytest.raises(ElasticityError):
        compute_elastic_config(
            {"elasticity": {"enabled": True, "max_train_batch_size": 100,
                            "micro_batch_sizes": [2], "version": 0.2}},
            __version__)


def test_engine_enforces_elastic_batch(devices):
    """Engine init must reject a train_batch_size that conflicts with
    the elastic batch (ref: engine check at runtime/engine.py:425)."""
    params = simple_model_params(hidden_dim=16)
    cfg = {
        "train_batch_size": 16,  # conflicts with elastic 1848
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "elasticity": {"enabled": True, "max_train_batch_size": 2000,
                       "micro_batch_sizes": [2, 4, 6], "version": 0.1},
    }
    with pytest.raises(ValueError, match="elastic batch size"):
        deepspeed_tpu.initialize(model=simple_model_loss,
                                 model_parameters=params, config=cfg)
    cfg["elasticity"]["ignore_non_elastic_batch_info"] = True
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    engine.train_batch(random_batch(16, 16))
