"""Two-process jax.distributed smoke tests — the multi-host simulator.

SURVEY §4 takeaway (1): the reference forks N local processes and runs real
NCCL through them (ref tests/unit/common.py:66 @distributed_test). The TPU
analog spawns real OS processes that rendezvous through
``jax.distributed.initialize`` on CPU devices, so ``jax.process_count() > 1``
paths (bootstrap env discovery, cross-process mesh, global-batch placement,
engine training) execute for real — not under a monkeypatched process index.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu.utils import distributed as dist

    dist.init_distributed()   # picks up DSTPU_* env
    rank = dist.get_rank()
    world = dist.get_world_size()
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())

    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tp = int(os.environ.get("DSTPU_TEST_TP", "1"))
    ds_cfg = {"train_batch_size": 8,
              "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": int(os.environ.get(
                  "DSTPU_TEST_STAGE", "1"))},
              "mesh": {"tensor_parallel_size": tp},
              "steps_per_print": 10_000}
    comm = os.environ.get("DSTPU_TEST_COMM")
    if comm:
        ds_cfg["comm_backend_name"] = comm
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config=ds_cfg,
        partition_rules=gpt.gpt_partition_rules() if tp > 1 else None)

    tokens = np.random.default_rng(0).integers(
        0, 128, (8, 17)).astype(np.int32)   # same global batch on every host
    losses = []
    for _ in range(int(os.environ.get("DSTPU_TEST_STEPS", "3"))):
        m = engine.train_batch({"tokens": tokens})
        losses.append(float(m["loss"]))

    qkv = engine.state.params["block"]["qkv"]["kernel"]
    print("RESULT " + json.dumps({
        "rank": rank, "world": world, "global_devices": n_global,
        "local_devices": n_local, "losses": losses,
        "qkv_shard": list(qkv.sharding.shard_shape(qkv.shape))}))
""")


def _spawn(num_procs, extra_env=None, worker=WORKER):
    port = _free_port()
    procs = []
    for pid in range(num_procs):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DSTPU_COORDINATOR": f"127.0.0.1:{port}",
            "DSTPU_NUM_PROCESSES": str(num_procs),
            "DSTPU_PROCESS_ID": str(pid),
            "DSTPU_TEST_REPO": REPO,
        })
        env.update(extra_env or {})
        # drop any preset single-process device forcing from conftest
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = {}
    errs = {}
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=600)
            errs[pid] = (p.returncode, err)
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for p in procs:   # a hung/failed rank must not orphan the others
            if p.poll() is None:
                p.kill()
    for pid, (rc, err) in errs.items():
        assert rc == 0 and pid in results, \
            f"rank {pid} rc={rc}\n{err[-2000:]}"
    return results


@pytest.mark.parametrize("stage", [1, 3])
def test_two_process_training(stage):
    """2 procs x 2 CPU devices: rendezvous, 4-device global mesh, 3 engine
    steps; every process sees the same loss trajectory (pure DP)."""
    results = _spawn(2, extra_env={"DSTPU_TEST_STAGE": str(stage)})
    assert results[0]["world"] == 2
    assert results[0]["global_devices"] == 4
    assert results[0]["local_devices"] == 2
    assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                 rel=1e-5)
    # training actually progresses
    assert results[0]["losses"][-1] < results[0]["losses"][0]


def test_two_process_tensor_parallel():
    """tp=2 x dp=2 on a 2-process global mesh: Megatron partition rules
    shard the params over the (intra-process) model axis while data
    parallelism crosses the process boundary — the multi-process mesh
    plumbing with real TP sharding active (asserted on the qkv shard)."""
    results = _spawn(2, extra_env={"DSTPU_TEST_TP": "2"})
    assert results[0]["global_devices"] == 4
    # qkv [L, d, 3d] = [2, 32, 96] column-shards to 48 over model=2
    assert results[0]["qkv_shard"][2] == 48
    assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                 rel=1e-5)
    assert results[0]["losses"][-1] < results[0]["losses"][0]


SP_WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu.utils import distributed as dist
    dist.init_distributed()
    rank = dist.get_rank()

    import dataclasses
    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=1, sequence=4))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False,
                        sequence_parallel=True,
                        sp_impl=os.environ["DSTPU_TEST_SP"], mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    # dense single-host oracle for the first step's loss
    cfg0 = dataclasses.replace(cfg, sequence_parallel=False, mesh=None)
    tokens = np.random.default_rng(0).integers(
        0, 128, (4, 33)).astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(tokens)},
                            jax.random.PRNGKey(0), cfg0,
                            deterministic=True))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 4,
                "mesh": {"sequence_parallel_size": 4},
                "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
                "steps_per_print": 10_000},
        mesh=mesh)
    losses = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(4)]
    print("RESULT " + json.dumps({"rank": rank, "losses": losses,
                                  "dense_ref": ref}))
""")


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_two_process_sequence_parallel(impl):
    """Sequence parallelism with the 'sequence' axis CROSSING the process
    boundary (2 procs x 2 devices, sp=4): the ring's ppermute rotation /
    Ulysses' all-to-alls run through real inter-process collectives — the
    multi-host long-context path. First loss must equal the dense
    single-host oracle and both ranks must agree."""
    results = _spawn(2, extra_env={"DSTPU_TEST_SP": impl},
                     worker=SP_WORKER)
    assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                 rel=1e-5)
    assert results[0]["losses"][0] == pytest.approx(
        results[0]["dense_ref"], rel=1e-4)
    assert results[0]["losses"][-1] < results[0]["losses"][0]


FSDPX_WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu.utils import distributed as dist
    dist.init_distributed()
    rank = dist.get_rank()

    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MESH_AXES

    # Mesh where the FSDP axis crosses the process boundary: fsdp
    # partners (adjacent in the minor mesh dim) live in DIFFERENT
    # processes, so ZeRO-3's param gathers and the exact grad
    # reduce-scatter run through real inter-process collectives while
    # the 1-bit 'data' wire crosses processes too.
    devs = jax.devices()
    by_proc = [[d for d in devs if d.process_index == p] for p in (0, 1)]
    order = [by_proc[0][0], by_proc[1][0], by_proc[0][1], by_proc[1][1]]
    mesh = jax.sharding.Mesh(
        np.asarray(order).reshape(1, 2, 2, 1, 1), MESH_AXES)

    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "comm_backend_name": "dcn_compressed",
                "zero_optimization": {"stage": 3,
                                      "stage3_min_shard_size": 1},
                "steps_per_print": 10_000},
        mesh=mesh)

    tokens = np.random.default_rng(0).integers(
        0, 128, (8, 17)).astype(np.int32)
    losses = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(3)]
    qkv = engine.state.params["block"]["qkv"]["kernel"]
    fsdp_cross = [d.process_index for d in qkv.sharding.device_set]
    print("RESULT " + json.dumps({
        "rank": rank, "losses": losses,
        "qkv_shard": list(qkv.sharding.shard_shape(qkv.shape)),
        "param_procs": sorted(set(fsdp_cross))}))
""")


def test_two_process_dcn_compressed_fsdp_crossing():
    """Compressed x fsdp with the FSDP axis crossing the process
    boundary (VERDICT r4 #4): ZeRO-3 param sharding + exact grad
    reduction over inter-process fsdp collectives, 1-bit error-feedback
    wire over 'data' — and the trajectory must match the identical
    single-process global arithmetic, because process placement is a
    layout choice, not a math change."""
    results = _spawn(2, worker=FSDPX_WORKER)
    assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                 rel=1e-5)
    assert results[0]["losses"][-1] < results[0]["losses"][0]
    # params genuinely sharded, across BOTH processes
    assert results[0]["param_procs"] == [0, 1]

    # single-process oracle: same global mesh shape / config / data on 4
    # of this process's virtual devices
    import numpy as np
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MESH_AXES

    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(1, 2, 2, 1, 1), MESH_AXES)
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=2, d_model=32,
                        max_seq_len=32, dtype=jnp.float32,
                        use_flash_attention=False, remat=False)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "comm_backend_name": "dcn_compressed",
                "zero_optimization": {"stage": 3,
                                      "stage3_min_shard_size": 1},
                "steps_per_print": 10_000},
        mesh=mesh)
    tokens = np.random.default_rng(0).integers(
        0, 128, (8, 17)).astype(np.int32)
    oracle = [float(engine.train_batch({"tokens": tokens})["loss"])
              for _ in range(3)]
    assert results[0]["losses"] == pytest.approx(oracle, rel=1e-5)


@pytest.mark.parametrize("stage", ["1", "2"])
def test_two_process_dcn_compressed(stage):
    """The compressed wire path (comm_backend_name='dcn_compressed')
    across REAL process boundaries — the DCN scenario it exists for
    (ref: runtime/comm/mpi.py multi-node compressed backend) — at ZeRO
    stages 1 AND 2 (stage 2 is one beyond the reference's 1-bit
    restriction: its gradient partitioning dissolves into the sharded
    opt update outside the manual region). Error feedback is stateful
    and lossy, so we assert convergence and cross-rank agreement plus
    closeness to the plain path, not bit-parity."""
    steps = "10"
    comp = _spawn(2, extra_env={"DSTPU_TEST_COMM": "dcn_compressed",
                                "DSTPU_TEST_STEPS": steps,
                                "DSTPU_TEST_STAGE": stage})
    plain = _spawn(2, extra_env={"DSTPU_TEST_STEPS": steps})
    # every rank sees the identical compressed trajectory
    assert comp[0]["losses"] == pytest.approx(comp[1]["losses"], rel=1e-5)
    # it learns, and lands near the uncompressed trajectory
    assert comp[0]["losses"][-1] < comp[0]["losses"][0]
    assert comp[0]["losses"][-1] < max(plain[0]["losses"][-1] * 1.5, 0.5)
