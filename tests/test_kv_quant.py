"""int8 paged KV-cache quantization unit tests (tentpole:
ops/quantizer.py KV helpers + the scale-aware pool layout in
inference/paged_cache.py + the dequantize-in-kernel paged attention in
ops/attention/paged.py).

The quantizer helpers are checked against a pure-numpy re-derivation
(round-trip error bound, exact re-round stability, live-mask zeroing);
the kernel tests run the pallas flash-decode in INTERPRET mode with
int8 pools + scales against the fp gather reference, bounding the
attention-output error by the per-block quantization step
(docs/KV_QUANT.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import gpt
from deepspeed_tpu.inference.paged_cache import PagedKVCache
from deepspeed_tpu.ops import quantizer
from deepspeed_tpu.ops.attention.paged import (paged_decode_attention,
                                               paged_decode_reference,
                                               paged_hbm_bytes_per_token,
                                               paged_verify_attention,
                                               paged_verify_reference)
from deepspeed_tpu.ops.quantizer import (kv_block_scales,
                                         kv_dequantize_blocks,
                                         kv_quantize_blocks,
                                         kv_requantize_blocks,
                                         resolve_kv_quant)


def tiny(**over):
    return gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                         max_seq_len=64, use_flash_attention=False,
                         remat=False, dtype=jnp.float32, **over)


# ---------------------------------------------------------------------------
# knob resolution
# ---------------------------------------------------------------------------

def test_resolve_kv_quant(monkeypatch):
    monkeypatch.delenv("DS_KV_QUANT", raising=False)
    assert resolve_kv_quant(None) == "off"            # default: off
    assert resolve_kv_quant("int8") == "int8"
    assert resolve_kv_quant(True) == "int8"
    assert resolve_kv_quant(False) == "off"
    monkeypatch.setenv("DS_KV_QUANT", "int8")
    assert resolve_kv_quant(None) == "int8"
    assert resolve_kv_quant("off") == "off"           # explicit beats env
    monkeypatch.setenv("DS_KV_QUANT", "fp4")
    with pytest.raises(ValueError, match="DS_KV_QUANT"):
        resolve_kv_quant(None)


# ---------------------------------------------------------------------------
# numpy-reference round trips
# ---------------------------------------------------------------------------

def _np_roundtrip(x):
    """Independent numpy re-derivation of the block quant math."""
    absmax = np.max(np.abs(x), axis=(-3, -1))
    scale = absmax / 127.0
    safe = np.where(scale > 0, scale, 1.0)[..., None, :, None]
    q = np.clip(np.round(x / safe), -127, 127).astype(np.int8)
    return q, scale, q.astype(np.float32) * scale[..., None, :, None]


def test_kv_quant_matches_numpy_reference(rng):
    x = rng.normal(size=(5, 8, 2, 16)).astype(np.float32) * 3.0
    q_ref, s_ref, deq_ref = _np_roundtrip(x)
    s = kv_block_scales(jnp.asarray(x))
    q = kv_quantize_blocks(jnp.asarray(x), s)
    deq = kv_dequantize_blocks(q, s)
    np.testing.assert_allclose(np.asarray(s), s_ref, rtol=1e-6)
    # round(x/s) at exact .5 boundaries may tie-break differently
    # between numpy and XLA; bound by one quantization level instead
    assert int(np.sum(np.asarray(q).astype(np.int32)
                      != q_ref.astype(np.int32))) == 0 or \
        np.max(np.abs(np.asarray(q).astype(np.int32)
                      - q_ref.astype(np.int32))) <= 1
    np.testing.assert_allclose(np.asarray(deq), deq_ref,
                               atol=float(s_ref.max()), rtol=0)


def test_kv_quant_roundtrip_error_bound(rng):
    """|dequant - original| <= scale/2 elementwise — the tolerance
    model every downstream parity bound builds on."""
    x = rng.normal(size=(7, 8, 4, 8)).astype(np.float32) * 10.0
    q, s = kv_requantize_blocks(jnp.asarray(x))
    deq = np.asarray(kv_dequantize_blocks(q, s))
    err = np.abs(deq - x)
    bound = (np.asarray(s) / 2.0 + 1e-7)[..., None, :, None]
    assert (err <= bound).all(), float((err - bound).max())


def test_kv_quant_exact_requant_stability():
    """Re-quantizing a dequantized block with the SAME scale is exact:
    the read-modify-requantize write path replays untouched lanes
    bit-identically as long as the block absmax doesn't move."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, 8, 2, 8)).astype(np.float32)
    q1, s1 = kv_requantize_blocks(jnp.asarray(x))
    deq = kv_dequantize_blocks(q1, s1)
    q2 = kv_quantize_blocks(deq, s1)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))


def test_kv_quant_live_mask_drops_stale_lanes():
    """A freed block's garbage (huge stale values) must not inflate the
    new owner's scale: requantize with a live mask zeroes dead token
    rows BEFORE the absmax."""
    x = np.ones((1, 8, 2, 4), np.float32)
    x[0, 5:] = 1e6                                    # stale garbage
    live = jnp.asarray(np.arange(8) < 5)[None]
    q, s = kv_requantize_blocks(jnp.asarray(x), live)
    assert float(jnp.max(s)) == pytest.approx(1.0 / 127.0)
    deq = np.asarray(kv_dequantize_blocks(q, s))
    np.testing.assert_allclose(deq[0, :5], 1.0, atol=1e-2)
    np.testing.assert_array_equal(deq[0, 5:], 0.0)    # zeroed, not 1e6


def test_kv_quant_zero_block_is_safe():
    """The all-zero trash block yields scale 0 and finite round trips
    (the guarded divide) — no NaN/inf ever enters the pool."""
    z = jnp.zeros((2, 8, 2, 4), jnp.float32)
    q, s = kv_requantize_blocks(z)
    assert float(jnp.max(jnp.abs(s))) == 0.0
    assert np.isfinite(np.asarray(q)).all()
    np.testing.assert_array_equal(
        np.asarray(kv_dequantize_blocks(q, s)), 0.0)


# ---------------------------------------------------------------------------
# int8 pool layout + budget accounting
# ---------------------------------------------------------------------------

def test_paged_cache_int8_pool_layout(devices):
    cfg = tiny()
    c = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=6,
                     kv_quant="int8")
    assert c.pool_dtype == jnp.int8
    assert c.k.dtype == jnp.int8 and c.v.dtype == jnp.int8
    assert c.k_scale.shape == (cfg.n_layers, c.num_blocks, cfg.kv_heads)
    assert c.k_scale.dtype == jnp.float32
    assert c.bytes_per_token == gpt.kv_bytes_per_token(cfg, jnp.int8)
    assert c.scale_bytes_per_block == 2 * cfg.n_layers * cfg.kv_heads * 4
    # off mode: no scale pools, fp pool dtype, zero scale overhead
    c0 = PagedKVCache(cfg, num_slots=2, block_size=4, num_blocks=6,
                      kv_quant="off")
    assert c0.k_scale is None and c0.scale_bytes_per_block == 0
    assert c0.pool_dtype == c0.dtype


def test_paged_cache_int8_budget_doubles_blocks(devices):
    """At the same HBM budget the int8 pool admits ~4x the fp32 blocks
    (2x vs a bf16 pool), minus the fp32 scale sidecar — the capacity
    headline, derived from the allocator's own arithmetic."""
    cfg = tiny()
    per_tok_fp = gpt.kv_bytes_per_token(cfg, jnp.float32)
    budget = per_tok_fp * 4 * 10          # exactly 10 fp32 4-token blocks
    fp = PagedKVCache(cfg, num_slots=2, block_size=4,
                      hbm_budget_bytes=budget, dtype=jnp.float32,
                      kv_quant="off")
    q = PagedKVCache(cfg, num_slots=2, block_size=4,
                     hbm_budget_bytes=budget, dtype=jnp.float32,
                     kv_quant="int8")
    assert fp.free_blocks == 10          # budget // per_block (+trash)
    per_block_q = (gpt.kv_bytes_per_token(cfg, jnp.int8) * 4
                   + q.scale_bytes_per_block)
    assert q.free_blocks == budget // per_block_q
    assert q.free_blocks >= int(1.8 * fp.free_blocks)
    # usage accounting includes the scale sidecar
    q.allocate(0, 6)
    assert q.used_block_bytes() == 2 * per_block_q


def test_paged_hbm_bytes_per_token_dtype_aware():
    cfg = tiny()
    fp = paged_hbm_bytes_per_token(cfg, 4, 32.0, 64, dtype=jnp.float32,
                                   impl="pallas")
    i8 = paged_hbm_bytes_per_token(cfg, 4, 32.0, 64, dtype=jnp.int8,
                                   impl="pallas")
    assert fp == 4 * i8                   # pure dtype ratio, no scales
    scale_b = 2 * cfg.n_layers * cfg.kv_heads * 4
    i8s = paged_hbm_bytes_per_token(cfg, 4, 32.0, 64, dtype=jnp.int8,
                                    impl="pallas", block_size=8,
                                    scale_bytes_per_block=scale_b)
    assert i8 < i8s < fp                  # scale sidecar amortized per token


# ---------------------------------------------------------------------------
# kernel parity: int8 pools through the pallas flash-decode (interpret)
# ---------------------------------------------------------------------------

def _quant_pool_problem(seed=0, B=3, Hkv=2, group=2, Dh=32, bs=8, NB=4):
    """fp pools + their int8 twins with per-(block, kv_head) scales;
    same distinct-table/trash-block-0 geometry as test_paged_attention's
    _pool_problem."""
    rng = np.random.default_rng(seed)
    N = B * NB + 1
    q = jnp.asarray(rng.normal(size=(B, Hkv, group, Dh)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(N, bs, Hkv, Dh)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(N, bs, Hkv, Dh)), jnp.float32)
    ids = rng.permutation(np.arange(1, N))
    tables = jnp.asarray(ids.reshape(B, NB), jnp.int32)
    lengths = jnp.asarray([bs // 2, bs * 2 + 1, bs * NB - 1], jnp.int32)
    kq, ks = kv_requantize_blocks(kp)
    vq, vs = kv_requantize_blocks(vp)
    return q, kp, vp, kq, ks, vq, vs, tables, lengths


def test_paged_kernel_int8_matches_quant_reference(devices,
                                                   pallas_interpret):
    """The kernel's in-register dequantize == the gather reference over
    the SAME int8 pools: only softmax reassociation apart (allclose at
    the fp parity tolerance, not the quant tolerance)."""
    q, _, _, kq, ks, vq, vs, tables, lengths = _quant_pool_problem()
    out = paged_decode_attention(q, kq, vq, tables, lengths, scale=0.25,
                                 k_scale=ks, v_scale=vs)
    ref = paged_decode_reference(q, kq, vq, tables, lengths, scale=0.25,
                                 k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_paged_kernel_int8_error_vs_fp_is_bounded(devices,
                                                  pallas_interpret):
    """int8 attention output vs the unquantized fp reference: the error
    is bounded by a small multiple of the largest quantization step
    (attention outputs are convex combinations of dequantized V rows,
    perturbed by the K-step through the softmax; docs/KV_QUANT.md)."""
    q, kp, vp, kq, ks, vq, vs, tables, lengths = _quant_pool_problem()
    out_q = paged_decode_attention(q, kq, vq, tables, lengths, scale=0.25,
                                   k_scale=ks, v_scale=vs)
    out_fp = paged_decode_reference(q, kp, vp, tables, lengths, scale=0.25)
    step = float(jnp.maximum(jnp.max(ks), jnp.max(vs)))
    err = float(np.max(np.abs(np.asarray(out_q) - np.asarray(out_fp))))
    assert err <= 8.0 * step, (err, step)


@pytest.mark.parametrize("G", [2, 3])
def test_paged_verify_int8_matches_quant_reference(devices,
                                                   pallas_interpret, G):
    q, _, _, kq, ks, vq, vs, tables, lengths = _quant_pool_problem()
    B, Hkv, group, Dh = q.shape
    rng = np.random.default_rng(7)
    qg = jnp.asarray(rng.normal(size=(B, G, Hkv, group, Dh)), jnp.float32)
    lengths = jnp.maximum(lengths - G, 0)
    out = paged_verify_attention(qg, kq, vq, tables, lengths, scale=0.25,
                                 k_scale=ks, v_scale=vs)
    ref = paged_verify_reference(qg, kq, vq, tables, lengths, scale=0.25,
                                 k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
