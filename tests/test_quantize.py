"""MoQ quantization tests (ref: tests/unit/test_moq* — absent in the
reference at this version; kernel behavior verified against the python
fallback math of deepspeed/runtime/quantize.py:158-205 instead)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops import quantizer as qops
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.quantize import Quantizer
from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
from tests.simple_model import random_batch, simple_model_loss, simple_model_params


# ---------------------------------------------------------------- ops

def test_fake_quant_roundtrip_error(rng):
    x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    for bits, tol in [(8, 1e-2), (12, 1e-3), (16, 1e-4)]:
        q = qops.quantize_dequantize(x, groups=4, bits=bits)
        assert q.shape == x.shape and q.dtype == x.dtype
        # error bounded by half a quantization step per group
        err = float(jnp.max(jnp.abs(q - x)))
        step = 2 * float(jnp.max(jnp.abs(x))) / (2 ** bits)
        assert err <= step + tol, (bits, err, step)


def test_fake_quant_asymmetric(rng):
    x = jnp.asarray(rng.standard_normal((32, 32)) + 3.0, jnp.float32)
    q = qops.quantize_dequantize(x, groups=2, bits=8, symmetric=False)
    assert float(jnp.max(jnp.abs(q - x))) < 0.1


def test_stochastic_rounding_unbiased():
    # a value strictly between two quantization levels: SR must land on
    # both neighbours with the right frequencies → mean ≈ value
    # (one 1.0 element pins the group scale so 0.3 stays interior)
    x = jnp.concatenate([jnp.full((1023,), 0.3, jnp.float32),
                         jnp.ones((1,), jnp.float32)])
    vals = []
    for i in range(20):
        q = qops.quantize_dequantize(x, groups=1, bits=4, stochastic=True,
                                     rng=jax.random.PRNGKey(i))
        vals.append(float(jnp.mean(q[:1023])))
    assert abs(np.mean(vals) - 0.3) < 0.02, np.mean(vals)


def test_int8_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    q, scale = qops.quantize(x, groups=8, bits=8)
    assert q.dtype == jnp.int8 and scale.shape == (8,)
    back = qops.dequantize(q, scale, groups=8, dtype=jnp.float32)
    assert float(jnp.max(jnp.abs(back - x))) < 0.05


def test_asym_int8_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((16, 32)) * 0.5 + 2.0, jnp.float32)
    q, scale, gmin = qops.quantize_asym(x, groups=4, bits=8)
    back = qops.dequantize_asym(q, scale, gmin, groups=4, dtype=jnp.float32)
    assert float(jnp.max(jnp.abs(back - x))) < 0.05


def test_quantized_matmul(rng):
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    qw, scale = qops.quantize(w, groups=16, bits=8)
    out = qops.quantized_matmul(x, qw, scale, groups=16)
    rel = float(jnp.linalg.norm(out - x @ w) / jnp.linalg.norm(x @ w))
    assert rel < 0.02, rel


def test_ste_gradient_is_identity(rng):
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    g = jax.grad(lambda t: jnp.sum(
        qops.quantize_dequantize_ste(t, groups=1, bits=8)))(x)
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x))


# ---------------------------------------------------------- scheduler

def test_moq_schedule_anneals_with_period_doubling():
    q = Quantizer(q_start_bits=12, q_target_bits=8, q_period=12,
                  q_offset=0, q_groups=1)
    params = {"w": jnp.ones((8, 8), jnp.float32) * 0.37,
              "b": jnp.ones((8,), jnp.float32)}
    seen_bits = []
    for _ in range(40):
        params = q.quantize_tree(params)
        seen_bits.append(q.q_start_bits[0])
    # anneals one bit per (doubling) period down to the target
    assert seen_bits[0] == 12 and seen_bits[-1] == 8
    assert sorted(set(seen_bits), reverse=True) == [12, 11, 10, 9, 8]
    # 1-D leaves untouched
    np.testing.assert_allclose(np.asarray(params["b"]), 1.0)


def test_moq_offset_warmup():
    q = Quantizer(q_start_bits=8, q_target_bits=8, q_offset=100)
    x = {"w": jnp.full((4, 4), 0.123, jnp.float32)}
    out = q.quantize_tree(x)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.123)  # still warmup


def test_moq_overflow_skips():
    q = Quantizer(q_start_bits=8, q_target_bits=8, q_offset=0)
    x = {"w": jnp.full((4, 4), 0.123, jnp.float32)}
    out = q.quantize_tree(x, overflow=True)
    assert out is x


def test_moq_mixed_fp16_ratio_decay():
    q = Quantizer(q_start_bits=8, q_target_bits=8, q_offset=0,
                  q_mixed_fp16=True, q_change_ratio=0.5)
    assert q.quantize_real_ratio == 1.0
    x = {"w": jnp.full((4, 4), 0.2, jnp.float32)}
    q.quantize_tree(x)
    assert q.quantize_real_ratio == 0.5
    q.quantize_tree(x)
    assert q.quantize_real_ratio == 0.0


def test_moq_stacked_per_layer_bits():
    L = 2
    q = Quantizer(q_start_bits=10, q_target_bits=8, q_period=6, q_offset=0,
                  q_eigenvalue=True, layer_num=L, stacked_prefix="blocks")
    params = {"blocks": {"w": jnp.ones((L, 8, 8), jnp.float32) * 0.37}}
    # layer 1 is "sensitive" (ev→factor>1 slows its schedule)
    ev = {"blocks.w.0": (0.0, 0), "blocks.w.1": (1.0, 1)}
    for _ in range(8):
        params = q.quantize_tree(params, eigenvalue_enabled=True,
                                 block_eigenvalue=ev)
    assert q.q_start_bits[0] <= q.q_start_bits[1] <= 10
    assert q.q_period[1] > q.q_period[0]


# --------------------------------------------------------- eigenvalue

def test_eigenvalue_quadratic_blocks():
    """Hessian of 0.5*c_l*||w_l||^2 is c_l*I → dominant ev = c_l; after
    post-processing: c_l / max(c)."""
    L, n = 3, 8
    coeffs = jnp.asarray([1.0, 4.0, 2.0])
    params = {"blocks": {"w": jnp.ones((L, n), jnp.float32)}}

    def loss(p, batch, rng):
        w = p["blocks"]["w"]
        return 0.5 * jnp.sum(coeffs[:, None] * w * w)

    ev = Eigenvalue(max_iter=50, tol=1e-3, layer_name="blocks", layer_num=L)
    out = ev.compute_eigenvalue(loss, params, batch=None, rng=jax.random.PRNGKey(0))
    got = [out[f"blocks.w.{i}"][0] for i in range(L)]
    np.testing.assert_allclose(got, [0.25, 1.0, 0.5], atol=1e-2)


def test_eigenvalue_post_process_zero_maps_to_one():
    ev = Eigenvalue(layer_name="blocks", layer_num=1)
    assert ev.post_process([0.0, 2.0, -1.0]) == [1.0, 1.0, 0.5]


# ---------------------------------------------------- weight quantizer

def test_weight_quantization_merge(rng):
    wq = WeightQuantization(mlp_extra_grouping=True, mp_size=1)
    h = 16
    qkv = jnp.asarray(rng.standard_normal((h, 3 * h)), jnp.float32)
    dense = jnp.asarray(rng.standard_normal((h, h)), jnp.float32)
    h4h = jnp.asarray(rng.standard_normal((h, 4 * h)), jnp.float32)
    hh4 = jnp.asarray(rng.standard_normal((4 * h, h)), jnp.float32)
    wq.Quantize([qkv], 8, 2, key="attn.qkv.weight")
    wq.Quantize([dense], 8, 2, key="attn.out.weight")
    wq.Quantize([h4h], 8, 2, key="mlp.dense_h_to_4h.weight")
    wq.Quantize([hh4], 8, 2, key="mlp.dense_4h_to_h.weight")
    merged = wq.merge_scales()
    assert merged.shape[0] == 1 and merged.shape[1] == 4  # 1 layer, 4 slots


def test_weight_quantization_split_ranks_get_real_scales(rng):
    """With mlp_extra_grouping the mlp categories have 2x the groups of
    qkv/dense; every TP rank must still receive its own real (non-padding)
    scale chunk for every category (ref: weight_quantizer.py:84)."""
    wq = WeightQuantization(mlp_extra_grouping=True, mp_size=1)
    h = 16
    wq.Quantize([jnp.asarray(rng.standard_normal((h, 3 * h)), jnp.float32)],
                8, 2, key="attn.qkv.weight")
    wq.Quantize([jnp.asarray(rng.standard_normal((h, h)), jnp.float32)],
                8, 2, key="attn.out.weight")
    wq.Quantize([jnp.asarray(rng.standard_normal((h, 4 * h)), jnp.float32)],
                8, 2, key="mlp.dense_h_to_4h.weight")
    wq.Quantize([jnp.asarray(rng.standard_normal((4 * h, h)), jnp.float32)],
                8, 2, key="mlp.dense_4h_to_h.weight")
    split = wq.merge_scales_split(2)
    assert len(split) == 2
    # category rows: 0=qkv (2 groups), 1=dense (2), 2=mlp h4h (4), 3=mlp 4hh (4)
    qkv_full = np.asarray(wq.qkv_scales[0]).reshape(-1)
    m4hh_full = np.asarray(wq.mlp4hh_scales[0]).reshape(-1)
    for rank in range(2):
        rank_scales = np.asarray(split[rank])[0]  # [4, width]
        # mlp rows are the widest -> fully real, and must be the rank's
        # own chunk of the category scales, not padding zeros
        np.testing.assert_allclose(rank_scales[3], m4hh_full[2 * rank:2 * rank + 2])
        # qkv row: first chunk real, remainder zero-pad
        np.testing.assert_allclose(rank_scales[0][:1], qkv_full[rank:rank + 1])
        assert np.all(rank_scales[0][1:] == 0)


def test_weight_quantization_accuracy(rng):
    wq = WeightQuantization()
    w = jnp.asarray(rng.standard_normal((32, 32)), jnp.float32)
    [qw] = wq.Quantize([w], 8, 4, key="attn.out.weight")
    scale = 1.0 / wq.dense_scales[0].reshape(-1)
    back = qops.dequantize(qw, scale, groups=4, dtype=jnp.float32)
    assert float(jnp.max(jnp.abs(back - w))) < 0.05


# ------------------------------------------------- engine integration

def test_engine_moq_training(devices):
    params = simple_model_params(hidden_dim=16, nlayers=2)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
        "quantize_training": {
            "enabled": True,
            "quantize_bits_start": 12,
            "quantize_bits_target": 8,
            "quantize_schedule_offset": 0,
            "quantize_period": 5,
            "quantize_groups": 1,
        },
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    losses = []
    for i in range(30):
        m = engine.train_batch(random_batch(8, 16, seed=i % 4))
        losses.append(float(m["loss"]))
    assert engine.quantizer is not None
    assert engine.quantizer.q_start_bits[0] < 12  # schedule advanced
    assert losses[-1] < losses[0], losses  # still learns while quantized
    # fp32 masters are NOT quantized (ref: engine.py:1789-1800 quantizes
    # the bit16 copies; masters keep accumulating sub-quantum updates)
    w = engine.state.params["layer_0"]["kernel"]
    bits = engine.quantizer.q_start_bits[0]
    on_grid = qops.quantize_dequantize(w, groups=1, bits=bits)
    assert float(jnp.max(jnp.abs(on_grid - w))) > 1e-6, \
        "masters appear quantized — they must stay full precision"


def test_engine_moq_with_offload(devices):
    """MoQ composes with host-offloaded Adam (the exclusion VERDICT r2
    flagged): fake-quant transforms only the in-jit compute params; the
    host masters step at full precision; precision switches rebuild the
    grad-only program (ref: engine.py:1789-1800 + cpu_offload compose
    in the reference the same way)."""
    params = simple_model_params(hidden_dim=16, nlayers=2)
    cfg = {
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "cpu"}},
        "quantize_training": {
            "enabled": True,
            "quantize_bits_start": 12,
            "quantize_bits_target": 8,
            "quantize_schedule_offset": 0,
            "quantize_period": 5,
            "quantize_groups": 1,
        },
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg)
    assert engine.offload_enabled and engine.quantizer is not None
    losses = []
    for i in range(30):
        m = engine.train_batch(random_batch(8, 16, seed=i % 4))
        losses.append(float(m["loss"]))
    assert engine.quantizer.q_start_bits[0] < 12   # switches happened
    assert losses[-1] < losses[0], losses


# --------------------------------------------------------------------
# fused int8 dequant-matmul kernel (VERDICT r4 weak #6; ref analog:
# csrc/transformer/inference int8 qkv_gemm/mlp_gemm + dequantize.cu)
# --------------------------------------------------------------------

def test_int8_matmul_parity(devices):
    from deepspeed_tpu.ops.int8_matmul import (int8_matmul,
                                               int8_matmul_reference)
    rng = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(rng)
    M, K, N = 48, 256, 512        # M deliberately not a tile multiple
    x = jax.random.normal(k1, (M, K), jnp.float32)
    w = jax.random.normal(k2, (K, N), jnp.float32)
    a = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    scale = a / 127.0 + 1e-12
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    out = int8_matmul(x, q, scale, block_m=32, block_n=128, block_k=128,
                      interpret=True)
    ref = int8_matmul_reference(x, q, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert out.shape == (M, N)


def test_int8_matmul_bf16_activations(devices):
    from deepspeed_tpu.ops.int8_matmul import (int8_matmul,
                                               int8_matmul_reference)
    rng = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(rng)
    x = jax.random.normal(k1, (8, 128), jnp.bfloat16)
    w = jax.random.normal(k2, (128, 256), jnp.float32)
    scale = jnp.max(jnp.abs(w), axis=0, keepdims=True) / 127.0 + 1e-12
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    out = int8_matmul(x, q, scale, block_m=8, block_n=128, block_k=128,
                      interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = int8_matmul_reference(x, q, scale)
    # the kernel is MORE precise than the reference (fp32 accumulation +
    # fp32 post-scale vs the reference's bf16 per-element dequant), so
    # the delta is the reference's bf16 rounding — bound it accordingly
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=0.3)


def test_int8_dense_fused_matches_xla_path(devices, monkeypatch):
    """gpt._dense with DS_INT8_FUSED must equal the XLA-dequant path on a
    quantized entry (TPU gate bypassed via on_tpu monkeypatch +
    interpret-mode pallas)."""
    import deepspeed_tpu.models.gpt as gpt_mod
    from deepspeed_tpu.inference.engine import quantize_weights_int8

    w = jax.random.normal(jax.random.PRNGKey(1), (128, 256), jnp.float32)
    p = quantize_weights_int8({"block": {"e": {"kernel": w}}})["block"]["e"]
    h = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 128), jnp.float32)
    plain = gpt_mod._dense(h, p)

    monkeypatch.setenv("DS_INT8_FUSED", "1")
    monkeypatch.setattr("deepspeed_tpu.utils.on_tpu", lambda: True)
    import deepspeed_tpu.ops.int8_matmul as im
    orig = im.int8_matmul

    def interp(x, q, scale, **kw):
        kw["interpret"] = True
        return orig(x, q, scale, **kw)

    monkeypatch.setattr(im, "int8_matmul", interp)
    fused = gpt_mod._dense(h, p)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(plain),
                               rtol=2e-5, atol=2e-5)
