"""Zigzag ring layout tests on the 8-device CPU mesh.

The zigzag layout (ops/attention/ring.py module docstring) balances the
causal triangle: device d holds chunks (d, 2n-1-d) of 2n, so every
device does equal attention work at every ring step. These tests assert
the permuted computation is EXACTLY standard causal attention: run the
ring on zigzag-permuted inputs, unpermute, compare against the dense
single-device reference on the original order.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.flash import mha_reference
from deepspeed_tpu.ops.attention.ring import (
    ring_attention, zigzag_perm, zigzag_unperm)
from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh


def _qkv(B=2, S=64, H=2, D=16, seed=0, dtype=jnp.float32, Hkv=None):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv or H, D), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv or H, D), dtype)
    return q, k, v


def test_perm_roundtrip():
    for S, n in [(16, 2), (64, 8), (48, 4), (4, 1)]:
        p = zigzag_perm(S, n)
        assert sorted(p.tolist()) == list(range(S))
        np.testing.assert_array_equal(p[zigzag_unperm(S, n)],
                                      np.arange(S))
        # device d's shard is [chunk d, chunk 2n-1-d]
        C = S // (2 * n)
        for d in range(n):
            sh = p[d * 2 * C:(d + 1) * 2 * C]
            assert sh[0] == d * C and sh[C] == (2 * n - 1 - d) * C


@pytest.mark.parametrize("n_seq", [2, 4, 8])
def test_zigzag_matches_dense(devices, n_seq):
    q, k, v = _qkv()
    S = q.shape[1]
    p, ip = zigzag_perm(S, n_seq), zigzag_unperm(S, n_seq)
    mesh = make_mesh(MeshSpec(data=8 // n_seq, sequence=n_seq))
    out = ring_attention(q[:, p], k[:, p], v[:, p], mesh, causal=True,
                         layout="zigzag")[:, ip]
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_zigzag_grads_match_dense(devices):
    q, k, v = _qkv(B=1, S=32, H=2, D=8)
    S, n_seq = q.shape[1], 8
    p, ip = zigzag_perm(S, n_seq), zigzag_unperm(S, n_seq)
    mesh = make_mesh(MeshSpec(data=1, sequence=n_seq))

    g_ring = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q[:, p], k[:, p], v[:, p], mesh, causal=True,
                       layout="zigzag") ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_zigzag_packed_segments_and_padding(devices):
    """Packing metadata permutes with the tokens and stays exact, with a
    segment boundary landing INSIDE a zigzag chunk."""
    B, S, n_seq = 2, 64, 4
    q, k, v = _qkv(B=B, S=S)
    segs = jnp.asarray(
        np.concatenate([np.zeros((B, 23), np.int32),
                        np.ones((B, 30), np.int32),
                        2 * np.ones((B, 11), np.int32)], axis=1))
    kvm = jnp.asarray((np.arange(S)[None, :] < 57).astype(np.float32)
                      * np.ones((B, 1), np.float32))
    p, ip = zigzag_perm(S, n_seq), zigzag_unperm(S, n_seq)
    mesh = make_mesh(MeshSpec(data=2, sequence=n_seq))
    out = ring_attention(q[:, p], k[:, p], v[:, p], mesh, causal=True,
                         segment_ids=segs[:, p], kv_mask=kvm[:, p],
                         layout="zigzag")[:, ip]
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs,
                        kv_mask=kvm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_zigzag_gqa(devices):
    q, k, v = _qkv(B=1, S=64, H=4, D=8, Hkv=2)
    S, n_seq = q.shape[1], 8
    p, ip = zigzag_perm(S, n_seq), zigzag_unperm(S, n_seq)
    mesh = make_mesh(MeshSpec(data=1, sequence=n_seq))
    out = ring_attention(q[:, p], k[:, p], v[:, p], mesh, causal=True,
                         layout="zigzag")[:, ip]
    kr = jnp.repeat(k, 2, axis=2)
    vr = jnp.repeat(v, 2, axis=2)
    ref = mha_reference(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_zigzag_with_data_parallel_axes(devices):
    q, k, v = _qkv(S=32)
    n_seq = 4
    p, ip = zigzag_perm(32, n_seq), zigzag_unperm(32, n_seq)
    mesh = make_mesh(MeshSpec(data=2, sequence=n_seq))
    out = ring_attention(q[:, p], k[:, p], v[:, p], mesh, causal=True,
                         layout="zigzag")[:, ip]
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_zigzag_rejects_window_and_noncausal(devices):
    q, k, v = _qkv(S=32)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh, causal=True, window=8,
                       layout="zigzag")
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh, causal=False, layout="zigzag")
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh, causal=True, layout="spiral")


def test_zigzag_gpt_trains(devices):
    """GPT under zigzag ring SP: first loss matches the dense oracle
    exactly (fp32), and training decreases it. The batch carries
    explicitly permuted tokens/targets and positions=perm."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    n_seq, S = 4, 64
    mesh = make_mesh(MeshSpec(data=2, sequence=n_seq))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, sp_layout="zigzag",
                        mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    cfg_dense = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4,
                              d_model=32, max_seq_len=64,
                              use_flash_attention=False, remat=False,
                              dtype=jnp.float32)
    toks = np.random.default_rng(0).integers(0, 128, (8, S + 1))
    toks = toks.astype(np.int32)
    ref = float(gpt.loss_fn(params, {"tokens": jnp.asarray(toks)},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))

    p = zigzag_perm(S, n_seq)
    batch = {"tokens": toks[:, :S][:, p],
             "targets": toks[:, 1:][:, p],
             "positions": np.broadcast_to(p.astype(np.int32), (8, S))}
    ds = {"train_batch_size": 8,
          "mesh": {"sequence_parallel_size": n_seq},
          "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
          "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params, config=ds,
        mesh=mesh)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-5)
    assert losses[-1] < losses[0] - 0.3


def test_zigzag_requires_positions(devices):
    from deepspeed_tpu.models import gpt
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=1, n_heads=2, d_model=16,
                        max_seq_len=32, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, sp_layout="zigzag",
                        mesh=mesh)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((4, 32), jnp.int32)
    with pytest.raises(ValueError, match="zigzag"):
        gpt.forward(params, toks, cfg, jax.random.PRNGKey(0),
                    deterministic=True)


def test_zigzag_batch_packed_parity(devices):
    """zigzag_batch(pack_documents(...)) under zigzag ring SP reproduces
    the dense packed loss exactly: derive-then-permute keeps targets,
    segment masks, restart positions and the loss mask aligned."""
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.runtime.dataloader import (pack_documents,
                                                  zigzag_batch)
    n_seq = 4
    mesh = make_mesh(MeshSpec(data=2, sequence=n_seq))
    cfg = gpt.GPTConfig(vocab_size=256, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32,
                        sequence_parallel=True, sp_layout="zigzag",
                        mesh=mesh)
    cfg_dense = gpt.GPTConfig(vocab_size=256, n_layers=2, n_heads=4,
                              d_model=32, max_seq_len=64,
                              use_flash_attention=False, remat=False,
                              dtype=jnp.float32)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(3)
    docs = [r.integers(0, 256, n).astype(np.int32)
            for n in (30, 21, 14, 40, 25, 9, 33, 17)]
    packed = pack_documents(docs, seq_len=65, pad_token=0)
    zig = zigzag_batch(packed, n_seq)
    assert set(zig) == {"tokens", "targets", "positions", "segment_ids",
                        "loss_mask"}
    loss = float(gpt.loss_fn(params, {k: jnp.asarray(v)
                                      for k, v in zig.items()},
                             jax.random.PRNGKey(0), cfg,
                             deterministic=True))
    ref = float(gpt.loss_fn(params, {k: jnp.asarray(v)
                                     for k, v in packed.items()},
                            jax.random.PRNGKey(0), cfg_dense,
                            deterministic=True))
    np.testing.assert_allclose(loss, ref, rtol=1e-5)
