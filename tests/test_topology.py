"""Topology math tests — no devices needed
(ref: tests/unit/test_topology.py)."""

import pytest

from deepspeed_tpu.parallel.topology import (PipeDataParallelTopology,
                                             PipeModelDataParallelTopology,
                                             PipelineParallelGrid,
                                             ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3


def test_topology_dims():
    topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("b") == 3
    assert topo.get_dim("missing") == 0


def test_topology_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    # ranks: (pipe,data) -> 0:(0,0) 1:(0,1) 2:(1,0) 3:(1,1)
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert [0, 2] in pipe_lists and [1, 3] in pipe_lists
    data_lists = topo.get_axis_comm_lists("data")
    assert [0, 1] in data_lists and [2, 3] in data_lists


def test_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    ranks = topo.filter_match(pipe=0)
    assert len(ranks) == 4
    assert all(topo.get_coord(r).pipe == 0 for r in ranks)


def test_grid_basic():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=0)
    assert grid.pipe_parallel_size == 4
    assert grid.data_parallel_size == 2
    assert grid.is_first_stage()
    assert not grid.is_last_stage()
    last = PipelineParallelGrid(topo, global_rank=topo.get_rank(pipe=3, data=0))
    assert last.is_last_stage()


def test_grid_stage_to_global():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=topo.get_rank(pipe=1, data=1))
    assert grid.get_stage_id() == 1
    nxt = grid.stage_to_global(2)
    assert topo.get_coord(nxt).pipe == 2
    assert topo.get_coord(nxt).data == 1


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    r = topo.get_rank(pipe=1, data=0, model=1)
    assert topo.get_rank_repr(rank=r) == "pipe_01-model_01"


def test_p2p_groups():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=1)
    grid = PipelineParallelGrid(topo, global_rank=0)
    assert grid.p2p_groups  # adjacent-stage pairs exist
    for g in grid.p2p_groups:
        assert len(g) == 2
