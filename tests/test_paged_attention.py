"""Paged-attention flash-decode kernel tests (tentpole:
ops/attention/paged.py + the impl switch through inference/engine.py and
inference/serving.py).

The kernel runs in INTERPRET mode here (JAX_PLATFORMS=cpu, see
conftest.py) — same kernel body, Python-evaluated — so tier-1 exercises
the pallas path without a TPU. The gather path is the bit-reference:
kernel-level tests are allclose (the online softmax reassociates the
reduction), serving-level tests assert token-for-token EQUALITY of the
greedy stream, including across an eviction/requeue."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.ops.attention.paged import (paged_decode_attention,
                                               paged_decode_reference,
                                               resolve_decode_impl)


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def prompts_of(lengths, seed=1):
    r = np.random.default_rng(seed)
    return [r.integers(1, 128, n).astype(np.int32) for n in lengths]


def _pool_problem(seed=0, B=3, Hkv=2, group=2, Dh=32, bs=8, NB=4):
    """Random pools + per-slot DISTINCT block tables (trash block 0 kept
    out of every table) + lengths hitting a partial block, a mid block
    and the last slot of the last block."""
    rng = np.random.default_rng(seed)
    N = B * NB + 1
    q = jnp.asarray(rng.normal(size=(B, Hkv, group, Dh)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(N, bs, Hkv, Dh)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(N, bs, Hkv, Dh)), jnp.float32)
    ids = rng.permutation(np.arange(1, N))
    tables = jnp.asarray(ids.reshape(B, NB), jnp.int32)
    lengths = jnp.asarray([bs // 2, bs * 2 + 1, bs * NB - 1], jnp.int32)
    return q, kp, vp, tables, lengths


# ---------------------------------------------------------------------------
# kernel unit tests (interpret mode — the tier-1 CPU smoke of the kernel)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("window", [None, 9])
def test_paged_kernel_matches_reference(devices, pallas_interpret, window):
    """Flash-decode through the block table == dense gathered softmax,
    at partial-block, mid-block and full-last-block lengths."""
    q, kp, vp, tables, lengths = _pool_problem()
    out = paged_decode_attention(q, kp, vp, tables, lengths,
                                 scale=q.shape[-1] ** -0.5, window=window)
    ref = paged_decode_reference(q, kp, vp, tables, lengths,
                                 scale=q.shape[-1] ** -0.5, window=window)
    assert out.shape == ref.shape == q.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_paged_kernel_mha_single_group(devices, pallas_interpret):
    """group == H//Hkv == 1 (plain MHA) and group == H (MQA) both hit
    the packed-matmul path."""
    for Hkv, group in ((4, 1), (1, 4)):
        q, kp, vp, tables, lengths = _pool_problem(Hkv=Hkv, group=group)
        out = paged_decode_attention(q, kp, vp, tables, lengths, scale=0.25)
        ref = paged_decode_reference(q, kp, vp, tables, lengths, scale=0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_paged_kernel_ignores_stale_blocks(devices, pallas_interpret):
    """Positions past lengths[b] never contribute: poisoning every pool
    slot beyond each slot's length (including whole table entries the
    clamped index_map re-reads) leaves the output bit-identical."""
    q, kp, vp, tables, lengths = _pool_problem()
    out = paged_decode_attention(q, kp, vp, tables, lengths, scale=0.25)
    bs = kp.shape[1]
    kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
    for b in range(tables.shape[0]):
        pos = int(lengths[b])
        for j in range(tables.shape[1]):
            bid = int(tables[b, j])
            for s in range(bs):
                if j * bs + s > pos:
                    kp2[bid, s] = 1e4
                    vp2[bid, s] = -1e4
    out2 = paged_decode_attention(q, jnp.asarray(kp2), jnp.asarray(vp2),
                                  tables, lengths, scale=0.25)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_paged_kernel_no_dense_gather(devices):
    """Acceptance: the pallas path never materializes the virtual
    [B, NB*block, ...] cache — its jaxpr contains no gather the size of
    pool[tables] (the reference path's first op)."""
    q, kp, vp, tables, lengths = _pool_problem()
    B, NB = tables.shape
    bs, Hkv, Dh = kp.shape[1], kp.shape[2], kp.shape[3]
    dense = (B, NB, bs, Hkv, Dh)

    def gathers(fn):
        jaxpr = jax.make_jaxpr(fn)(q, kp, vp, tables, lengths)
        return [e for e in jaxpr.jaxpr.eqns
                if e.primitive.name == "gather"
                and tuple(e.outvars[0].aval.shape) == dense]

    assert gathers(lambda *a: paged_decode_reference(*a, scale=0.25))
    assert not gathers(lambda *a: paged_decode_attention(
        *a, scale=0.25, interpret=True))


def test_resolve_decode_impl(devices, monkeypatch):
    assert resolve_decode_impl("gather") == "gather"
    assert resolve_decode_impl("pallas") == "pallas"
    monkeypatch.setenv("DS_PAGED_DECODE_IMPL", "pallas")
    assert resolve_decode_impl(None) == "pallas"
    monkeypatch.delenv("DS_PAGED_DECODE_IMPL")
    assert resolve_decode_impl(None) == "gather"    # CPU default
    with pytest.raises(ValueError, match="expected 'pallas' or 'gather'"):
        resolve_decode_impl("cuda")


# ---------------------------------------------------------------------------
# serving parity: pallas stream == gather stream, token for token
# ---------------------------------------------------------------------------

def _serve(impl, cfg, params, prompts, n_new, **srv_kw):
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    srv = ServingEngine(eng, decode_impl=impl, **srv_kw)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=n_new)
                   for i, p in enumerate(prompts)])
    return out, srv


def test_serving_parity_pallas_vs_gather(devices, pallas_interpret):
    """Greedy serving output is token-for-token identical under both
    impls — GQA + rotary + sliding window + chunked prefill all on, so
    the full feature stack flows through the kernel."""
    cfg, _ = tiny()
    cfg = dataclasses.replace(cfg, rotary_dim=4, use_wpe=False,
                              n_kv_heads=2, attn_window=10)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    prompts = prompts_of((4, 13, 7), seed=7)
    kw = dict(num_slots=2, block_size=4, num_blocks=30, prefill_chunk=4)
    ref, _ = _serve("gather", cfg, params, prompts, 8, **kw)
    out, srv = _serve("pallas", cfg, params, prompts, 8, **kw)
    assert srv.decode_impl == "pallas"
    for i in ref:
        np.testing.assert_array_equal(out[i], ref[i])
    assert srv.stats["peak_occupancy"] > 1    # batched decode really ran


def test_serving_parity_pallas_across_eviction(devices, pallas_interpret):
    """The eviction/requeue recompute path (tight pool, zero watermark)
    stays parity-exact under the pallas kernel."""
    cfg, params = tiny()
    p1, p2 = prompts_of((10, 9), seed=9)

    def run(impl):
        eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
        srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=7,
                            decode_impl=impl)
        srv.cache.watermark = 0
        out = srv.run([ServeRequest(rid="a", prompt=p1, max_new_tokens=12),
                       ServeRequest(rid="b", prompt=p2, max_new_tokens=10)])
        return out, srv.stats["evictions"]

    ref, ev_g = run("gather")
    out, ev_p = run("pallas")
    assert ev_g >= 1 and ev_p >= 1
    np.testing.assert_array_equal(out["a"], ref["a"])
    np.testing.assert_array_equal(out["b"], ref["b"])


def test_serving_engine_impl_defaults_to_engine(devices):
    """ServingEngine inherits the engine's resolved decode_impl (CPU
    default: gather) unless overridden."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    assert eng.decode_impl == "gather"
    assert ServingEngine(eng, num_slots=2).decode_impl == "gather"
    assert ServingEngine(eng, num_slots=2,
                         decode_impl="pallas").decode_impl == "pallas"
    with pytest.raises(ValueError):
        ServingEngine(eng, num_slots=2, decode_impl="nope")


# ---------------------------------------------------------------------------
# slot-capacity overflow (satellite): finish, don't clobber
# ---------------------------------------------------------------------------

def test_full_budget_slot_finished_not_overwritten(devices):
    """A decoding slot whose cache length has reached the per-slot block
    budget is FINISHED before the decode kernel runs — not preempted
    (the resume prompt is as long, it would requeue forever) and never
    allowed to clamp-write into its own last live block."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=40)
    req = ServeRequest(rid="full", prompt=prompts_of((8,))[0],
                       max_new_tokens=16)
    srv.submit(req)
    srv._admit()
    slot = srv.slots.index(req)
    # drive the slot to the edge of its block budget by hand
    srv.cache.ensure_capacity(slot, srv.cache.tokens_per_slot)
    srv.cache.lengths[slot] = srv.cache.tokens_per_slot
    req.state = "decode"
    req.out.append(1)
    used_before = srv.cache.used_blocks
    assert srv._decode_step(now=0.0) == 0     # nothing decoded
    assert req.state == "done" and req in srv.finished
    assert srv.slots[slot] is None
    assert srv.cache.used_blocks < used_before   # blocks back in the pool
    assert srv.stats["evictions"] == 0


@pytest.mark.parametrize("impl", ["gather", "pallas"])
def test_engine_masks_capacity_overflow_write(devices, pallas_interpret,
                                              impl):
    """Engine-side belt: decode_slots with lengths == NB*block routes
    the new token's K/V write to the trash block instead of clamping
    into the slot's last live block."""
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    bs, NB = 4, 3
    N = 8
    L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    rng = np.random.default_rng(0)
    kp = jnp.asarray(rng.normal(size=(L, N, bs, Hkv, Dh)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(L, N, bs, Hkv, Dh)), jnp.float32)
    tables = np.zeros((2, NB), np.int32)
    tables[0] = [1, 2, 3]
    tables[1] = [4, 5, 6]
    # slot 0 at FULL budget, slot 1 mid-block
    lengths = np.array([NB * bs, 5], np.int32)
    active = np.array([True, True])
    _, k2, v2 = eng.decode_slots(kp.copy(), vp.copy(), tables, lengths,
                                 np.array([3, 4], np.int32), active,
                                 impl=impl)
    # every block slot 0 owns is untouched (the overflow write went to
    # trash block 0); slot 1's current position DID get written
    np.testing.assert_array_equal(np.asarray(k2)[:, 1:4],
                                  np.asarray(kp)[:, 1:4])
    assert not np.array_equal(np.asarray(k2)[:, 5, 1],
                              np.asarray(kp)[:, 5, 1])
    assert not np.array_equal(np.asarray(v2)[:, 5, 1],
                              np.asarray(vp)[:, 5, 1])
