"""Checkpoint save/resume tests: loss continuity, elastic reload, and
crash consistency (ref: tests/unit/test_checkpointing.py — save/load
across zero stages, optimizers, schedulers; loss continuity across
resume). The crash tests drive the ``checkpoint.pre_commit`` /
``checkpoint.commit`` fault-injection sites: a save killed at either
point must leave the directory in a state every loader survives."""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.checkpointing import (
    CheckpointError, get_latest_tag, list_tags,
    load_fp32_state_dict_from_zero_checkpoint, validate_tag)
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault, InjectedCrash
from tests.simple_model import random_batch, simple_model_loss, simple_model_params

HIDDEN = 32

BASE = {
    "train_batch_size": 16,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
    "steps_per_print": 1000,
}


def _make_engine(config, seed=0):
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=seed)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=config)
    return engine


@pytest.mark.parametrize("stage", [0, 1, 3])
def test_save_resume_loss_continuity(tmp_path, devices, stage):
    cfg = dict(BASE)
    if stage:
        cfg["zero_optimization"] = {"stage": stage, "stage3_min_shard_size": 1}
    engine = _make_engine(cfg)
    for i in range(5):
        engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    engine.save_checkpoint(str(tmp_path), tag="t5", client_state={"note": "hi"})

    # continue training: reference trajectory
    ref_losses = [float(engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))["loss"])
                  for i in range(5, 8)]

    # fresh engine, load, replay — must match exactly
    engine2 = _make_engine(cfg, seed=123)  # different init to prove load works
    path, client = engine2.load_checkpoint(str(tmp_path), tag="t5")
    assert path is not None
    assert client == {"note": "hi"}
    assert engine2.global_steps == 5
    new_losses = [float(engine2.train_batch(random_batch(16, HIDDEN, seed=i % 4))["loss"])
                  for i in range(5, 8)]
    np.testing.assert_allclose(ref_losses, new_losses, rtol=1e-6)


def test_latest_tag(tmp_path, devices):
    engine = _make_engine(dict(BASE))
    engine.train_batch(random_batch(16, HIDDEN))
    engine.save_checkpoint(str(tmp_path))  # default tag: global_step1
    assert get_latest_tag(str(tmp_path)) == "global_step1"
    engine2 = _make_engine(dict(BASE), seed=9)
    path, _ = engine2.load_checkpoint(str(tmp_path))  # latest
    assert path is not None and path.endswith("global_step1")


def test_missing_checkpoint_returns_none(tmp_path, devices):
    engine = _make_engine(dict(BASE))
    path, client = engine.load_checkpoint(str(tmp_path))
    assert path is None and client == {}


def test_elastic_reshard_stage3_to_dp(tmp_path, devices):
    """Save under ZeRO-3 (sharded), reload into a replicated (stage 0)
    engine — the 'elastic checkpoint' capability
    (ref: stage_1_and_2.py:2002 _restore_from_elastic_fp32_weights)."""
    cfg3 = dict(BASE)
    cfg3["zero_optimization"] = {"stage": 3, "stage3_min_shard_size": 1}
    engine = _make_engine(cfg3)
    for i in range(3):
        engine.train_batch(random_batch(16, HIDDEN, seed=i))
    engine.save_checkpoint(str(tmp_path), tag="z3")
    loss_ref = float(engine.eval_batch(random_batch(16, HIDDEN, seed=7))[0])

    engine0 = _make_engine(dict(BASE), seed=55)
    engine0.load_checkpoint(str(tmp_path), tag="z3")
    loss0 = float(engine0.eval_batch(random_batch(16, HIDDEN, seed=7))[0])
    np.testing.assert_allclose(loss_ref, loss0, rtol=1e-5)


def test_zero_to_fp32_consolidation(tmp_path, devices):
    """Offline consolidation (zero_to_fp32.py analog)."""
    cfg = dict(BASE)
    cfg["zero_optimization"] = {"stage": 3, "stage3_min_shard_size": 1}
    engine = _make_engine(cfg)
    engine.train_batch(random_batch(16, HIDDEN))
    engine.save_checkpoint(str(tmp_path), tag="c")
    sd = load_fp32_state_dict_from_zero_checkpoint(str(tmp_path), tag="c")
    assert sd["head"]["kernel"].shape == (HIDDEN, 1)
    assert sd["head"]["kernel"].dtype == np.float32
    # matches live params
    live = np.asarray(engine.state.params["head"]["kernel"])
    np.testing.assert_allclose(live, sd["head"]["kernel"], rtol=1e-6)


def test_scheduler_state_resumes(tmp_path, devices):
    """LR schedule position survives save/resume (ref: test_checkpointing
    scheduler matrix) — the resumed engine's lr continues, not restarts."""
    cfg = dict(BASE)
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_num_steps": 20,
                                   "warmup_max_lr": 1e-2}}
    engine = _make_engine(cfg)
    for i in range(6):
        m = engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    lr_before = float(m["lr"])
    engine.save_checkpoint(str(tmp_path), tag="s")

    engine2 = _make_engine(cfg, seed=77)
    engine2.load_checkpoint(str(tmp_path), tag="s")
    m2 = engine2.train_batch(random_batch(16, HIDDEN, seed=6 % 4))
    # next step's lr must continue the warmup from step 6, not step 0
    assert float(m2["lr"]) > lr_before


def test_fp16_loss_scale_resumes(tmp_path, devices):
    """Dynamic loss-scale state is part of the checkpoint (ref fp16
    optimizer state_dict round-trip)."""
    cfg = dict(BASE)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                   "loss_scale_window": 2}
    engine = _make_engine(cfg)
    for i in range(5):
        engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    scale = float(engine.state.scale_state.loss_scale)
    engine.save_checkpoint(str(tmp_path), tag="f")

    engine2 = _make_engine(cfg, seed=3)
    engine2.load_checkpoint(str(tmp_path), tag="f")
    np.testing.assert_allclose(
        float(engine2.state.scale_state.loss_scale), scale)


def test_memory_efficient_bf16_resumes(tmp_path, devices):
    """bf16 memory_efficient (bf16 params+moments, stochastic rounding)
    checkpoints round-trip with loss continuity."""
    cfg = dict(BASE)
    cfg["bf16"] = {"enabled": True, "memory_efficient": True}
    engine = _make_engine(cfg)
    for i in range(4):
        engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    engine.save_checkpoint(str(tmp_path), tag="me")
    ref = [float(engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))["loss"])
           for i in range(4, 6)]
    engine2 = _make_engine(cfg, seed=21)
    engine2.load_checkpoint(str(tmp_path), tag="me")
    got = [float(engine2.train_batch(random_batch(16, HIDDEN, seed=i % 4))["loss"])
           for i in range(4, 6)]
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_moe_model_checkpoint(tmp_path, devices):
    """MoE (expert-stacked) params round-trip through the engine
    checkpoint (ref: _save_moe_checkpoint per-expert files)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import moe_gpt

    cfg = moe_gpt.MoEGPTConfig(
        vocab_size=64, n_layers=2, n_heads=2, d_model=16, max_seq_len=16,
        dtype=jnp.float32, use_flash_attention=False, remat=False,
        num_experts=4, moe_k=1)
    ds = {"train_batch_size": 8,
          "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
          "steps_per_print": 1000}
    params = moe_gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params, config=ds)
    tokens = {"tokens": np.random.default_rng(0).integers(
        0, 64, (8, 17)).astype(np.int32)}
    for _ in range(3):
        eng.train_batch(tokens)
    eng.save_checkpoint(str(tmp_path), tag="moe")
    ref = float(eng.train_batch(tokens)["loss"])

    params2 = moe_gpt.init_params(jax.random.PRNGKey(5), cfg)
    eng2, _, _, _ = deepspeed_tpu.initialize(
        model=moe_gpt.make_loss_fn(cfg), model_parameters=params2, config=ds)
    eng2.load_checkpoint(str(tmp_path), tag="moe")
    got = float(eng2.train_batch(tokens)["loss"])
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_save_16bit_model_roundtrip(devices, tmp_path):
    """save_16bit_model consolidates sharded weights into one flat npz
    (ref: engine.py:3136) and load_16bit_model restores the exact
    pytree incl. bf16 leaves."""
    import deepspeed_tpu
    from deepspeed_tpu.runtime.checkpointing import load_16bit_model
    from tests.simple_model import (random_batch, simple_model_loss,
                                    simple_model_params)

    params = simple_model_params(hidden_dim=16, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params,
        config={"train_batch_size": 8, "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3,
                                      "stage3_min_shard_size": 1},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "steps_per_print": 1000})
    engine.train_batch(random_batch(8, 16, seed=0))
    assert engine.save_16bit_model(str(tmp_path))

    import jax
    loaded = load_16bit_model(str(tmp_path / "model_weights.npz"))
    ref = engine.consolidated_16bit_state_dict()
    flat_ref = jax.tree_util.tree_flatten_with_path(ref)[0]
    n = 0
    for path, leaf in flat_ref:
        node = loaded
        from deepspeed_tpu.runtime.checkpointing import _flat_key
        for part in _flat_key(path).split("/"):
            node = node[part]
        assert node.dtype == np.asarray(leaf).dtype
        np.testing.assert_array_equal(node, np.asarray(leaf))
        n += 1
    assert n > 0


def test_memory_efficient_bf16_elastic_topology_change(tmp_path, devices):
    """The HEADLINE training mode (bf16.memory_efficient + ZeRO-3: bf16
    params + stochastically-rounded bf16 moments) restored across a
    TOPOLOGY change — 8-way fsdp -> 2-way fsdp on half the devices, the
    restart-after-shrink scenario (VERDICT r4 #9; ref:
    stage_1_and_2.py:2002 _restore_from_elastic_fp32_weights /
    _restore_elastic_base_optimizer_state). The restored engine must
    continue the loss trajectory: moments and rng are part of the
    checkpoint, and orbax reshards them onto the new mesh."""
    import jax
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = dict(BASE)
    cfg["bf16"] = {"enabled": True, "memory_efficient": True}
    cfg["zero_optimization"] = {"stage": 3, "stage3_min_shard_size": 1}
    engine = _make_engine(cfg)                     # 8-way fsdp mesh
    for i in range(4):
        engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    engine.save_checkpoint(str(tmp_path), tag="me8")
    ref = [float(engine.train_batch(
        random_batch(16, HIDDEN, seed=i % 4))["loss"])
        for i in range(4, 7)]

    mesh2 = make_mesh(MeshSpec(data=1, fsdp=2), devices=jax.devices()[:2])
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=99)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg,
        mesh=mesh2)
    path, _ = engine2.load_checkpoint(str(tmp_path), tag="me8")
    assert path is not None
    assert engine2.global_steps == 4
    # moments restored in the memory-efficient dtype, resharded 2-way
    mom = [x for x in jax.tree_util.tree_leaves(engine2.state.opt_state)
           if getattr(x, "ndim", 0) == 2]
    assert mom and all(m.dtype == jax.numpy.bfloat16 for m in mom), \
        "memory_efficient moments must stay bf16 across elastic restore"
    got = [float(engine2.train_batch(
        random_batch(16, HIDDEN, seed=i % 4))["loss"])
        for i in range(4, 7)]
    # bf16 + stochastic rounding: the restored rng stream is identical,
    # but fsdp=2 vs 8 changes reduction order at bf16 precision — allow
    # bf16-level slack, not drift
    np.testing.assert_allclose(ref, got, rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# crash consistency: injected crashes at the commit boundaries
# ---------------------------------------------------------------------------

def test_crash_between_commit_and_latest_lands_on_previous_tag(
        tmp_path, devices):
    """A crash AFTER the tag dir commits but BEFORE ``latest`` updates
    (the classic torn-pointer window): the new tag is durable on disk,
    but the pointer still names the previous checkpoint — a plain
    reload lands there, with the state it had at save time."""
    engine = _make_engine(dict(BASE))
    engine.train_batch(random_batch(16, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path), tag="t1")
    engine.train_batch(random_batch(16, HIDDEN, seed=1))
    with faults_lib.injected(Fault("checkpoint.commit", "crash")):
        with pytest.raises(InjectedCrash):
            engine.save_checkpoint(str(tmp_path), tag="t2")
    # t2 is fully committed and valid — only the pointer never moved
    assert validate_tag(str(tmp_path), "t2")
    assert get_latest_tag(str(tmp_path)) == "t1"
    engine2 = _make_engine(dict(BASE), seed=5)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("t1")
    assert engine2.global_steps == 1


def test_crash_pre_commit_leaves_no_visible_tag(tmp_path, devices):
    """A crash after the state write but BEFORE the tag dir commit: the
    half-written checkpoint exists only as ``<tag>.building`` — never a
    loadable tag, never a walk-back candidate — and a retried save
    succeeds over the leftover."""
    engine = _make_engine(dict(BASE))
    engine.train_batch(random_batch(16, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path), tag="t1")
    with faults_lib.injected(Fault("checkpoint.pre_commit", "crash")):
        with pytest.raises(InjectedCrash):
            engine.save_checkpoint(str(tmp_path), tag="t2")
    assert not os.path.isdir(tmp_path / "t2")
    assert os.path.isdir(tmp_path / "t2.building")   # staged leftover
    assert list_tags(str(tmp_path)) == ["t1"]
    assert get_latest_tag(str(tmp_path)) == "t1"
    # the retry cleans the leftover and commits normally
    engine.save_checkpoint(str(tmp_path), tag="t2")
    assert get_latest_tag(str(tmp_path)) == "t2"
    assert validate_tag(str(tmp_path), "t2")
    assert not os.path.isdir(tmp_path / "t2.building")


def test_corrupt_latest_tag_walks_back_to_valid(tmp_path, devices):
    """Bit rot / torn write in the newest tag: the manifest check
    rejects it and an implicit (latest) load walks back to the newest
    valid tag; an EXPLICIT request for the corrupt tag is never
    silently substituted, and ``strict=True`` raises."""
    engine = _make_engine(dict(BASE))
    engine.train_batch(random_batch(16, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path), tag="good")
    engine.train_batch(random_batch(16, HIDDEN, seed=1))
    engine.save_checkpoint(str(tmp_path), tag="bad")
    assert get_latest_tag(str(tmp_path)) == "bad"
    # corrupt one manifest-listed payload file in the newest tag
    with open(tmp_path / "bad" / "ds_meta.json", "a") as f:
        f.write(" ")
    assert not validate_tag(str(tmp_path), "bad")

    engine2 = _make_engine(dict(BASE), seed=7)
    path, _ = engine2.load_checkpoint(str(tmp_path))     # implicit latest
    assert path is not None and path.endswith("good")
    assert engine2.global_steps == 1
    # explicit tag: warn + (None, {}), or CheckpointError under strict
    engine3 = _make_engine(dict(BASE), seed=9)
    path, client = engine3.load_checkpoint(str(tmp_path), tag="bad")
    assert path is None and client == {}
    with pytest.raises(CheckpointError, match="manifest"):
        engine3.load_checkpoint(str(tmp_path), tag="bad", strict=True)


def test_strict_load_raises_on_empty_dir(tmp_path, devices):
    engine = _make_engine(dict(BASE))
    with pytest.raises(CheckpointError, match="latest"):
        engine.load_checkpoint(str(tmp_path), strict=True)
    # non-strict keeps the historical warn-and-None contract
    path, client = engine.load_checkpoint(str(tmp_path))
    assert path is None and client == {}


def test_fp16_scaler_elastic_topology_change(tmp_path, devices):
    """Dynamic loss-scale state survives a topology change too (the
    'scaler state' half of VERDICT r4 #9)."""
    import jax
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = dict(BASE)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 10,
                   "loss_scale_window": 2}
    cfg["zero_optimization"] = {"stage": 3, "stage3_min_shard_size": 1}
    engine = _make_engine(cfg)
    for i in range(5):
        engine.train_batch(random_batch(16, HIDDEN, seed=i % 4))
    scale = float(engine.state.scale_state.loss_scale)
    engine.save_checkpoint(str(tmp_path), tag="fp16e")

    mesh2 = make_mesh(MeshSpec(data=1, fsdp=2), devices=jax.devices()[:2])
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=31)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=cfg,
        mesh=mesh2)
    engine2.load_checkpoint(str(tmp_path), tag="fp16e")
    np.testing.assert_allclose(
        float(engine2.state.scale_state.loss_scale), scale)
    m = engine2.train_batch(random_batch(16, HIDDEN, seed=5 % 4))
    assert np.isfinite(float(m["loss"]))
