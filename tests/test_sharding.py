"""Sharding-spec inference tests: ZeRO stages as PartitionSpecs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import (MeshSpec, batch_sharding, dp_world_size,
                                         make_mesh, mesh_from_config)
from deepspeed_tpu.parallel.sharding import (PartitionRule, megatron_rules,
                                             opt_state_specs, param_specs)
from deepspeed_tpu.runtime.config import DeepSpeedConfig


def _params():
    return {
        "embed": {"embedding": jnp.zeros((4096, 256))},
        "attn": {"qkv": {"kernel": jnp.zeros((256, 768))},
                 "out_proj": {"kernel": jnp.zeros((256, 256))}},
        "ln": {"scale": jnp.zeros((256,))},
        "scalar": jnp.zeros(()),
    }


def test_mesh_resolution(devices):
    mesh = make_mesh(MeshSpec(data=-1))
    assert dp_world_size(mesh) == 8
    mesh2 = make_mesh(MeshSpec(data=-1, model=2))
    assert dp_world_size(mesh2) == 4


def test_mesh_from_config(devices):
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "zero_optimization": {"stage": 3}}, world_size=8)
    mesh = mesh_from_config(cfg)
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert shape["fsdp"] == 8 and shape["data"] == 1

    cfg2 = DeepSpeedConfig({"train_batch_size": 8,
                            "mesh": {"tensor_parallel_size": 2}}, world_size=4)
    mesh2 = mesh_from_config(cfg2)
    shape2 = dict(zip(mesh2.axis_names, mesh2.devices.shape))
    assert shape2["model"] == 2 and shape2["data"] == 4


def test_stage0_replicated(devices):
    mesh = make_mesh(MeshSpec())
    specs = param_specs(_params(), mesh, zero_stage=0)
    flat = jax.tree_util.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert all(all(e is None for e in s) for s in flat)


def test_stage3_shards_big_params(devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    specs = param_specs(_params(), mesh, zero_stage=3, min_shard_size=128)
    embed = specs["embed"]["embedding"]
    assert "fsdp" in [a for e in embed if e for a in
                      (e if isinstance(e, tuple) else (e,))]
    # scalars and small params stay replicated
    assert specs["scalar"] == P()


def test_tp_rules_apply(devices):
    mesh = make_mesh(MeshSpec(data=-1, model=2))
    specs = param_specs(_params(), mesh, zero_stage=0, rules=megatron_rules())
    assert specs["attn"]["qkv"]["kernel"] == P(None, "model")
    assert specs["attn"]["out_proj"]["kernel"] == P("model", None)


def test_tp_plus_fsdp(devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=4, model=2))
    specs = param_specs(_params(), mesh, zero_stage=3,
                        rules=megatron_rules(), min_shard_size=128)
    qkv = specs["attn"]["qkv"]["kernel"]
    # model on dim 1 from the rule, fsdp added on dim 0
    assert qkv == P("fsdp", "model")


def test_opt_state_sharded_stage1(devices):
    import optax
    mesh = make_mesh(MeshSpec(data=8))
    params = _params()
    pspecs = param_specs(params, mesh, zero_stage=1)
    opt = optax.adam(1e-3)
    ostate = jax.eval_shape(opt.init, params)
    ospecs = opt_state_specs(ostate, pspecs, params, mesh, zero_stage=1,
                             min_shard_size=128)
    leaves = jax.tree_util.tree_leaves(ospecs, is_leaf=lambda x: isinstance(x, P))
    # at least the embed-shaped moments should be sharded over 'data'
    sharded = [s for s in leaves
               if any("data" in ((e,) if not isinstance(e, tuple) else e)
                      for e in s if e is not None)]
    assert sharded, "no optimizer state got sharded under stage 1"


def test_params_actually_place(devices):
    """End-to-end placement: put a param tree with stage-3 specs."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    params = _params()
    from deepspeed_tpu.parallel.sharding import to_named
    specs = to_named(param_specs(params, mesh, zero_stage=3, min_shard_size=128), mesh)
    placed = jax.device_put(params, specs)
    emb = placed["embed"]["embedding"]
    # each device holds 1/8 of the embedding rows
    assert emb.sharding.shard_shape(emb.shape)[0] == 4096 // 8


def test_zero_init_materializes_sharded(devices):
    """zero.Init analog: params come into existence already partitioned —
    no device (and no host path) ever holds a full leaf
    (ref: partition_parameters.py:548)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(fsdp=4, model=2))
    cfg = gpt.GPTConfig(vocab_size=256, n_layers=2, n_heads=4, d_model=64,
                        max_seq_len=32, use_flash_attention=False)
    params = deepspeed_tpu.zero.Init(
        lambda k: gpt.init_params(k, cfg), jax.random.PRNGKey(0), mesh,
        zero_stage=3, rules=gpt.gpt_partition_rules(), min_shard_size=1)
    qkv = params["block"]["qkv"]["kernel"]
    # sharded at construction: per-device shard strictly smaller
    shard = qkv.sharding.shard_shape(qkv.shape)
    assert int(np.prod(shard)) < int(np.prod(qkv.shape))
    # trains through the engine unchanged
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=gpt.make_loss_fn(cfg), model_parameters=params,
        config={"train_batch_size": 8, "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3,
                                      "stage3_min_shard_size": 1},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 1000},
        mesh=mesh, partition_rules=gpt.gpt_partition_rules())
    tokens = np.random.default_rng(0).integers(0, 256, (8, 17)).astype(np.int32)
    m = eng.train_batch({"tokens": tokens})
    assert np.isfinite(float(m["loss"]))


def test_gqa_tensor_parallel_sharding(devices):
    """GQA x TP: the fused qkv projection has width (H + 2*Hkv)*Dh —
    Megatron column rules must still shard it over 'model', and training
    must match the unsharded model."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=4, model=2))
    ref_mesh = make_mesh(MeshSpec(data=4), devices=jax.devices()[:4])

    def build(tp):
        cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=8,
                            d_model=32, max_seq_len=32, n_kv_heads=2,
                            use_flash_attention=False, remat=False,
                            dtype=jnp.float32)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=gpt.make_loss_fn(cfg), model_parameters=params,
            config={"train_batch_size": 4,
                    "mesh": ({"data_parallel_size": 4,
                              "tensor_parallel_size": 2} if tp
                             else {"data_parallel_size": 4}),
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000},
            mesh=mesh if tp else ref_mesh,
            partition_rules=gpt.gpt_partition_rules() if tp else None)
        return eng, cfg

    e_tp, cfg = build(True)
    e_ref, _ = build(False)
    qkv = e_tp.state.params["block"]["qkv"]["kernel"]
    # qkv width = (8 + 2*2) * 4 = 48 -> 24 per model shard
    assert qkv.sharding.shard_shape(qkv.shape)[-1] == cfg.qkv_dim // 2
    data = {"tokens": np.random.default_rng(0).integers(
        0, 128, (4, 33)).astype(np.int32)}
    for _ in range(2):
        l_tp = float(e_tp.train_batch(data)["loss"])
        l_ref = float(e_ref.train_batch(data)["loss"])
        np.testing.assert_allclose(l_tp, l_ref, rtol=1e-4)
