"""ZeRO-Offload / ZeRO-Infinity tier tests.

Mirrors the reference's offload coverage (ref: tests/unit/test_zero.py
cpu_offload configs, tests/unit/test_aio.py swap paths): host Adam step
parity with the fused device path, swapper roundtrips, and engine training
convergence with cpu/nvme offload.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.swap_tensor import (OptimizerStateSwapper,
                                               PipelinedOptimizerSwapper,
                                               AsyncTensorSwapper)
from deepspeed_tpu.ops.aio import AsyncIOHandle
from tests.simple_model import (random_batch, simple_model_loss,
                                simple_model_params)

HIDDEN = 32


def test_optimizer_swapper_roundtrip(tmp_path):
    sw = OptimizerStateSwapper(str(tmp_path), n_tensors=2)
    rng = np.random.default_rng(0)
    m = rng.standard_normal(10_000).astype(np.float32)
    v = rng.standard_normal(10_000).astype(np.float32)
    sw.swap_out("layer0", [m, v])
    assert sw.has_state("layer0")
    m2, v2 = sw.swap_in("layer0")
    np.testing.assert_array_equal(m, m2)
    np.testing.assert_array_equal(v, v2)
    sw.purge()
    assert not sw.has_state("layer0")


def test_pipelined_swapper_prefetch(tmp_path):
    sw = PipelinedOptimizerSwapper(str(tmp_path), n_tensors=2)
    rng = np.random.default_rng(1)
    tensors = {}
    for i in range(4):
        m = rng.standard_normal(5000).astype(np.float32)
        v = rng.standard_normal(5000).astype(np.float32)
        tensors[str(i)] = (m, v)
        sw.swap_out(str(i), [m, v])
    # pipelined loop: prefetch i+1 while "computing" on i
    for i in range(4):
        if i + 1 < 4:
            sw.prefetch(str(i + 1))
        m, v = sw.swap_in(str(i))
        np.testing.assert_array_equal(m, tensors[str(i)][0])
        np.testing.assert_array_equal(v, tensors[str(i)][1])
        sw.swap_out_async(str(i), [m * 2, v * 2])
    sw.finish()
    m, v = sw.swap_in("2")
    np.testing.assert_array_equal(m, tensors["2"][0] * 2)


def test_async_tensor_swapper(tmp_path):
    aio = AsyncIOHandle()
    sw = AsyncTensorSwapper(aio, buffer_count=2, buffer_size=1 << 16)
    rng = np.random.default_rng(2)
    arrays = [rng.standard_normal(3000).astype(np.float32) for _ in range(5)]
    for i, a in enumerate(arrays):
        sw.swap_out(a, str(tmp_path / f"a{i}.swp"))
    sw.wait()
    for i, a in enumerate(arrays):
        out = np.empty_like(a)
        aio.sync_pread(out, str(tmp_path / f"a{i}.swp"))
        np.testing.assert_array_equal(a, out)
    aio.close()


def _train(config, steps=20, seed=0):
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=seed)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params, config=config)
    losses = []
    for i in range(steps):
        batch = random_batch(config["train_batch_size"], HIDDEN, seed=i % 4)
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    return engine, losses


def _base_config(**zero_extra):
    return {
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "zero_optimization": {"stage": 1, **zero_extra},
        "steps_per_print": 1000,
    }


def test_cpu_offload_trains():
    cfg = _base_config(offload_optimizer={"device": "cpu"})
    engine, losses = _train(cfg, steps=25)
    assert engine.offload_enabled
    assert losses[-1] < losses[0] * 0.5, losses


def test_cpu_offload_matches_fused_path():
    """Offloaded host-Adam trajectory tracks the fused device path
    (both bf16 compute; tolerances cover bf16 param rounding)."""
    cfg_off = _base_config(offload_optimizer={"device": "cpu"})
    _, losses_off = _train(cfg_off, steps=10)
    cfg_dev = _base_config()
    _, losses_dev = _train(cfg_dev, steps=10)
    np.testing.assert_allclose(losses_off, losses_dev, rtol=0.25, atol=0.05)


def test_nvme_offload_trains(tmp_path):
    cfg = _base_config(offload_optimizer={
        "device": "nvme", "nvme_path": str(tmp_path / "swap"),
        "pipeline_read": True})
    engine, losses = _train(cfg, steps=25)
    assert losses[-1] < losses[0] * 0.5, losses
    # moments really live on NVMe
    import os
    assert os.listdir(str(tmp_path / "swap"))


def test_offload_checkpoint_resume(tmp_path):
    cfg = _base_config(offload_optimizer={"device": "cpu"})
    engine, _ = _train(cfg, steps=8)
    engine.save_checkpoint(str(tmp_path / "ck"), tag="t8")

    params2 = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=1)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params2, config=cfg)
    engine2.load_checkpoint(str(tmp_path / "ck"), tag="t8")
    assert engine2.host_optimizer.step_count == engine.host_optimizer.step_count
    # masters are keyed per (leaf, shard) — compare shard-wise
    for a, b in zip(engine.host_optimizer.master,
                    engine2.host_optimizer.master):
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    # loss continuity: both engines produce the same next-step loss
    batch = random_batch(8, HIDDEN, seed=9)
    l1 = float(engine.train_batch(batch)["loss"])
    l2 = float(engine2.train_batch(batch)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=0.05, atol=0.02)


def test_sharded_offload_zero3():
    """ZeRO-3 param sharding (fsdp over the 8-device mesh) + host offload:
    masters live per shard, updated leaves are rebuilt onto the mesh
    (multi-host shard handling: only addressable shards are stepped,
    ref: per-DP-rank partitions stage_1_and_2.py:546)."""
    cfg = _base_config(offload_optimizer={"device": "cpu"})
    cfg["zero_optimization"]["stage"] = 3
    cfg["zero_optimization"]["stage3_min_shard_size"] = 1
    engine, losses = _train(cfg, steps=15)
    # at least one leaf should actually be sharded into >1 unique shard
    n_shards = [len(t.by_key) for t in engine.host_optimizer.tables]
    assert max(n_shards) > 1, n_shards
    assert losses[-1] < losses[0] * 0.6, losses
    # parity with the fused (non-offload) stage-3 path
    cfg_dev = _base_config()
    cfg_dev["zero_optimization"]["stage"] = 3
    cfg_dev["zero_optimization"]["stage3_min_shard_size"] = 1
    _, losses_dev = _train(cfg_dev, steps=15)
    np.testing.assert_allclose(losses, losses_dev, rtol=0.25, atol=0.05)


def test_adagrad_offload():
    """Host Adagrad offload (ref: csrc/adagrad/cpu_adagrad.cpp via the
    same offload machinery)."""
    cfg = _base_config(offload_optimizer={"device": "cpu"})
    cfg["optimizer"] = {"type": "adagrad", "params": {"lr": 5e-2}}
    engine, losses = _train(cfg, steps=25)
    assert engine.host_optimizer.optimizer_name == "adagrad"
    assert losses[-1] < losses[0] * 0.7, losses


def test_adagrad_offload_checkpoint_roundtrip(tmp_path):
    """Adagrad offload checkpoints restore (load_state/state_arrays on
    the host adagrad)."""
    cfg = _base_config(offload_optimizer={"device": "cpu"})
    cfg["optimizer"] = {"type": "adagrad", "params": {"lr": 5e-2}}
    engine, _ = _train(cfg, steps=5)
    engine.save_checkpoint(str(tmp_path / "ck"), tag="t5")
    params2 = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=1)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params2, config=cfg)
    engine2.load_checkpoint(str(tmp_path / "ck"), tag="t5")
    batch = random_batch(8, HIDDEN, seed=9)
    l1 = float(engine.train_batch(batch)["loss"])
    l2 = float(engine2.train_batch(batch)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=0.05, atol=0.02)


def test_sharded_offload_elastic_restore(tmp_path):
    """Moments checkpoint globally (topology-independent): save from a
    sharded stage-3 layout, restore into a DIFFERENT (unsharded stage-1)
    layout — the elastic-checkpoint contract
    (ref: stage_1_and_2.py:2074 _restore_elastic_base_optimizer_state)."""
    cfg3 = _base_config(offload_optimizer={"device": "cpu"})
    cfg3["zero_optimization"]["stage"] = 3
    cfg3["zero_optimization"]["stage3_min_shard_size"] = 1
    engine, _ = _train(cfg3, steps=6)
    assert max(len(t.by_key) for t in engine.host_optimizer.tables) > 1
    engine.save_checkpoint(str(tmp_path / "ck"), tag="t6")

    cfg1 = _base_config(offload_optimizer={"device": "cpu"})
    params2 = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=1)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params2, config=cfg1)
    engine2.load_checkpoint(str(tmp_path / "ck"), tag="t6")
    # moments restored (non-zero) and step continuity holds
    st = engine2.host_optimizer.state_dict()
    assert any(np.abs(v["exp_avg_sq"]).sum() > 0
               for v in st["state"].values())
    batch = random_batch(8, HIDDEN, seed=9)
    l1 = float(engine.train_batch(batch)["loss"])
    l2 = float(engine2.train_batch(batch)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=0.05, atol=0.02)


def test_shard_export_import_cross_topology():
    """The multi-host checkpoint path: shard pieces exported from a
    sharded (stage-3) layout merge losslessly into a different
    (unsharded stage-1) layout — no zero-filled regions survive."""
    cfg3 = _base_config(offload_optimizer={"device": "cpu"})
    cfg3["zero_optimization"]["stage"] = 3
    cfg3["zero_optimization"]["stage3_min_shard_size"] = 1
    engine, _ = _train(cfg3, steps=5)
    pieces = engine.host_optimizer.shard_export()
    assert len(pieces) > len(engine.host_optimizer.master)  # multi-shard

    cfg1 = _base_config(offload_optimizer={"device": "cpu"})
    params2 = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=1)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=simple_model_loss, model_parameters=params2, config=cfg1)
    engine2.host_optimizer.shard_import(
        pieces, engine.host_optimizer.step_count)
    # masters identical after merge
    for i in range(len(engine.host_optimizer.master)):
        a = engine.host_optimizer._global_master(i)
        b = engine2.host_optimizer._global_master(i)
        np.testing.assert_array_equal(a, b)
        m1 = engine.host_optimizer._global_moment(i, "exp_avg_sq")
        m2 = engine2.host_optimizer._global_moment(i, "exp_avg_sq")
        np.testing.assert_array_equal(m1, m2)
        assert np.abs(m1).sum() > 0  # moments actually carried over


def test_delayed_param_update():
    """DPU (ZeRO-Offload delayed param update): one-step-stale host Adam
    overlapped with the next step's device work still converges, and
    flush_delayed_update installs the pending update before
    checkpoint/eval."""
    cfg = _base_config(offload_optimizer={"device": "cpu",
                                          "delayed_param_update": True})
    engine, losses = _train(cfg, steps=25)
    assert engine.dpu_enabled
    assert losses[-1] < losses[0] * 0.6, losses
    # pending update exists mid-stream; flush installs it
    step_before = int(engine.state.step)
    engine.flush_delayed_update()
    assert engine._dpu_pending is None
    assert int(engine.state.step) == step_before + 1
    # eval after flush uses current params and is finite
    batch = random_batch(8, HIDDEN, seed=3)
    loss, _ = engine.eval_batch(batch)
    assert np.isfinite(float(loss))


def test_dpu_requires_bf16():
    cfg = _base_config(offload_optimizer={"device": "cpu",
                                          "delayed_param_update": True})
    cfg["bf16"] = {"enabled": False}
    cfg["fp16"] = {"enabled": True}
    params = simple_model_params(hidden_dim=HIDDEN, nlayers=2, seed=0)
    with pytest.raises(ValueError, match="delayed_param_update"):
        deepspeed_tpu.initialize(model=simple_model_loss,
                                 model_parameters=params, config=cfg)


def test_dpu_load_checkpoint_discards_pending(tmp_path):
    """A pending DPU update must never overwrite restored weights."""
    cfg = _base_config(offload_optimizer={"device": "cpu",
                                          "delayed_param_update": True})
    engine, _ = _train(cfg, steps=6)
    engine.save_checkpoint(str(tmp_path / "ck"), tag="t6")  # flushes
    saved = engine.host_optimizer._global_master(0).copy()
    # create a fresh pending update, then load over it
    engine.train_batch(random_batch(8, HIDDEN, seed=7))
    assert engine._dpu_pending is not None
    engine.load_checkpoint(str(tmp_path / "ck"), tag="t6")
    assert engine._dpu_pending is None
    np.testing.assert_array_equal(
        engine.host_optimizer._global_master(0), saved)
    # next step trains from the restored weights, not the stale update
    m = engine.train_batch(random_batch(8, HIDDEN, seed=8))
    assert np.isfinite(float(m["loss"]))


def test_step_pipeline_overlap_schedule():
    """The 3-stage overlap claim, asserted structurally: every shard's
    d2h copy is enqueued BEFORE the first Adam runs, and each leaf's
    updated h2d is in flight before the next leaf's Adam completes
    (ref overlap budget: pipelined_optimizer_swapper.py:60,
    stage_1_and_2.py:1005)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import MeshSpec, make_mesh
    from deepspeed_tpu.runtime.zero import offload as off

    mesh = make_mesh(MeshSpec(data=8))
    shard = NamedSharding(mesh, P("data"))
    params = {f"w{i}": np.arange(64, dtype=np.float32) + i
              for i in range(4)}
    shardings = {k: shard for k in params}
    opt = off.HostOffloadOptimizer(params, lr_schedule=lambda s: 1e-2,
                                   shardings=shardings)
    grads = {k: jax.device_put(np.full(64, 0.1, np.float32), shard)
             for k in params}

    events = []
    # the probe is global: a prior test's engine may still flush a DPU
    # background step (ds-dpu thread) — record main-thread events only
    import threading
    main = threading.main_thread()
    off._pipeline_probe = lambda ev, i, k: (
        events.append((ev, i, k))
        if threading.current_thread() is main else None)
    try:
        opt.step(grads)
    finally:
        off._pipeline_probe = None

    d2h = [j for j, e in enumerate(events) if e[0] == "d2h_enqueue"]
    adam = [j for j, e in enumerate(events) if e[0] == "adam_done"]
    assert d2h and adam
    # stage 1 completes before stage 2 starts: transfers all in flight
    assert max(d2h) < min(adam), events[:12]
    # leaf i's h2d enqueued before leaf i+1's first adam completes
    h2d_by_leaf = {}
    adam_first = {}
    for j, (ev, i, k) in enumerate(events):
        if ev == "h2d_enqueue":
            h2d_by_leaf.setdefault(i, j)
        if ev == "adam_done":
            adam_first.setdefault(i, j)
    for i in sorted(h2d_by_leaf)[:-1]:
        assert h2d_by_leaf[i] < adam_first[i + 1], (i, events)


def test_loopback_pipeline_efficiency():
    """The overlap claim enforced at ~0.9 of the measured headline:
    under an emulated serialized link the REAL step schedule must reach
    >=0.85 of the ideal two-stage pipeline bound and come in at <=0.89x
    the no-overlap serial model at two link speeds. (Measured at these
    parameters: efficiency 0.89-1.34, vs_serial 0.55-0.86 across trials
    — PERF.md headline 1.11/0.97 eff, 0.53x/0.83x serial at 1/4 GB/s on
    bigger shards. Best-of-3 absorbs host jitter; a regression to the
    old 0.65/0.9 floor now fails.) Source of truth is the tool's own
    run() — the same numbers its JSON line reports."""
    from tools.offload_loopback import run as loopback_run
    # link speeds chosen so t_transfer is comparable to t_adam for these
    # shard sizes — that's where overlap vs serial actually discriminates
    # (a negligible link makes both models collapse to t_adam)
    for bw in (0.5, 1.5):
        results = []
        for _ in range(3):            # best-of-3: host jitter happens
            eff, vs_serial = loopback_run(bw, n_leaves=6, elems=2_000_000)
            results.append((eff, vs_serial))
            if eff >= 0.85 and vs_serial <= 0.89:
                break
        # SOME trial must clear BOTH gates (a max-over-one-metric pick
        # could select a trial that fails the other gate even when a
        # fully-passing trial exists). 0.89 ceiling: worst observed
        # single trial is 0.861 — a few % slack for slower CI hosts
        # while still failing a real regression toward the serial
        # model (1.0).
        assert any(e >= 0.85 and v <= 0.89 for e, v in results), \
            (bw, results)
