"""Test harness: virtual 8-device CPU mesh.

TPU analog of the reference's multi-process fixture
(ref: tests/unit/common.py:66 @distributed_test forking N local processes).
On TPU/JAX we emulate a multi-chip host inside ONE process with
``xla_force_host_platform_device_count`` — every sharding/collective code
path compiles and runs exactly as on an 8-chip slice.

Must set env before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a real TPU  # dslint: disable=DS005 — must pin the platform BEFORE jax imports
flags = os.environ.get("XLA_FLAGS", "")  # dslint: disable=DS005 — bootstrap: XLA flags only apply pre-import
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"  # dslint: disable=DS005 — bootstrap: XLA flags only apply pre-import

import jax  # noqa: E402

# a sitecustomize may have imported jax (locking the platform choice from the
# env) before this conftest ran — override through the config instead.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 CI deselects with `-m 'not slow'`; the gate's full mode
    # runs everything
    config.addinivalue_line(
        "markers", "slow: heavy end-to-end test, excluded from tier-1")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Force pallas interpret mode on CPU (shared by the kernel parity
    suites)."""
    import functools

    import jax.experimental.pallas as pl

    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(pl.pallas_call, interpret=True))
    yield
