"""Shared-prefix KV cache tests (tentpole: refcounted block sharing +
radix prefix index + copy-on-write in inference/paged_cache.py /
inference/prefix_index.py, wired through the serving scheduler).

Layers:
  1. PrefixIndex unit tests — radix insert/match, mid-block partial
     (COW candidate) matching, LRU order, leaf-only eviction;
  2. refcount allocator — sharing increments refcounts, blocks held by
     any slot are NEVER reclaimed, double-free/foreign ids raise,
     free() is idempotent, stats() reports block states;
  3. serving integration — warm-vs-cold token parity (the acceptance
     gate: prefix hits change WORK DONE, never tokens produced), COW
     divergence mid-block, preempt/requeue of a sharing request, the
     compile-count contract with the cache on, and seeded chaos on the
     ``cache.match`` / ``cache.cow`` fault sites.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.paged_cache import (CacheExhausted,
                                                 PagedKVCache,
                                                 resolve_prefix_cache)
from deepspeed_tpu.inference.prefix_index import PrefixIndex
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine
from deepspeed_tpu.models import gpt
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.faults import Fault


def tiny(**over):
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, n_heads=4, d_model=32,
                        max_seq_len=64, use_flash_attention=False,
                        remat=False, dtype=jnp.float32, **over)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def eng(devices):
    cfg, params = tiny()
    return InferenceEngine(config=cfg, params=params, dtype=jnp.float32)


def _solo_refs(eng, prompts, n):
    return [eng.generate(p[None], max_new_tokens=n)[0] for p in prompts]


def toks(*vals):
    return np.asarray(vals, np.int32)


# ---------------------------------------------------------------------------
# PrefixIndex unit tests (pure host)
# ---------------------------------------------------------------------------

def test_index_insert_match_full_blocks():
    ix = PrefixIndex(block_size=4)
    t = np.arange(12, dtype=np.int32)
    assert ix.insert(t, [5, 6, 7]) == 3
    m = ix.match(t, max_tokens=12)
    assert m.block_ids == [5, 6, 7] and m.matched == 12
    assert m.cow_src is None
    # a shorter query stops at its own block boundary
    m = ix.match(t[:8], max_tokens=8)
    assert m.block_ids == [5, 6] and m.matched == 8
    # divergence at the FIRST token of a block: no chain past it
    t2 = t.copy()
    t2[4] = 99
    m = ix.match(t2, max_tokens=12)
    assert m.block_ids == [5] and m.matched == 4 and m.cow_src is None


def test_index_partial_match_is_cow_candidate():
    ix = PrefixIndex(block_size=4)
    t = np.arange(12, dtype=np.int32)
    ix.insert(t, [5, 6, 7])
    # diverges INSIDE block 1 (token 6): blocks [5] shared, block 6 is
    # the COW source with 2 reusable leading tokens
    t2 = t.copy()
    t2[6] = 99
    m = ix.match(t2, max_tokens=12)
    assert m.block_ids == [5] and m.cow_src == 6 and m.cow_tokens == 2
    assert m.matched == 4 + 2
    # max_tokens cap ends the match inside a fully-cached block: the
    # cached block becomes a COW source too (the len-1 admission cap)
    m = ix.match(t, max_tokens=11)
    assert m.block_ids == [5, 6] and m.cow_src == 7 and m.cow_tokens == 3
    # among sibling variants the LONGEST common run wins
    t3 = t.copy()
    t3[5] = 50
    ix.insert(t3, [5, 9, 0])              # only block 9 is new (chunk differs)
    q = t.copy()
    q[7] = 77
    m = ix.match(q, max_tokens=12)
    assert m.cow_src == 6 and m.cow_tokens == 3   # 3 common > t3's 1


def test_index_insert_dedups_and_rejects_reregistration():
    ix = PrefixIndex(block_size=4)
    t = np.arange(8, dtype=np.int32)
    assert ix.insert(t, [3, 4]) == 2
    # same chunks, different (private) blocks: nothing new registered
    assert ix.insert(t, [8, 9]) == 0
    assert ix.match(t, max_tokens=8).block_ids == [3, 4]
    # one physical block cannot serve two different chains
    with pytest.raises(ValueError, match="already registered"):
        ix.insert(toks(9, 9, 9, 9), [3])


def test_index_lru_leaf_only_eviction():
    ix = PrefixIndex(block_size=2)
    a = toks(1, 2, 3, 4)                  # chain 10 -> 11
    b = toks(1, 2, 9, 9)                  # chain 10 -> 12
    ix.insert(a, [10, 11])
    ix.insert(b, [10, 12])
    # interior node 10 is NOT evictable while its children live
    assert ix.pop_evictable(lambda bid: bid == 10) is None
    ix.match(b, max_tokens=4)             # touch 12 (and 10): 11 is LRU
    assert ix.pop_evictable(lambda bid: True) == 11
    assert ix.pop_evictable(lambda bid: True) == 12
    assert ix.pop_evictable(lambda bid: True) == 10   # exposed leaf last
    assert len(ix) == 0 and ix.pop_evictable(lambda bid: True) is None


def test_index_evictable_count_and_remove():
    ix = PrefixIndex(block_size=2)
    ix.insert(toks(1, 2, 3, 4), [5, 6])
    assert ix.evictable_count(lambda b: True) == 2
    assert ix.evictable_count(lambda b: b == 6) == 1
    assert not ix.remove_block(5)         # interior: refused
    assert ix.remove_block(6) and ix.remove_block(5)
    assert 5 not in ix and len(ix) == 0


# ---------------------------------------------------------------------------
# refcount allocator
# ---------------------------------------------------------------------------

def cache_of(num_blocks=16, block_size=4, watermark=0, **kw):
    cfg, _ = tiny()
    return PagedKVCache(cfg, num_slots=4, block_size=block_size,
                        num_blocks=num_blocks, dtype=jnp.float32,
                        watermark=watermark, prefix_cache=True, **kw)


def prefilled(c, slot, tokens):
    """allocate + pretend the prompt was prefilled + publish it."""
    m = c.allocate(slot, len(tokens), tokens=tokens)
    c.lengths[slot] = len(tokens)
    c.register_prefix(slot, tokens)
    return m


def test_allocator_sharing_increments_refcounts():
    c = cache_of()
    t = np.arange(16, dtype=np.int32)
    assert prefilled(c, 0, t) == 0                    # cold
    m = c.allocate(1, 16, tokens=t)
    # 3 full shared blocks + COW of the 4th (len-1 cap) = 15 tokens
    assert m == 15 and c.cow_copies == 1
    shared = c._owned[0][:3]
    assert c._owned[1][:3] == shared                  # same physical blocks
    assert all(c._refcount[b] == 2 for b in shared)
    assert c.shared_blocks == 3
    assert c.lengths[1] == 15                         # prefill resumes there
    c.free(1)
    assert all(c._refcount[b] == 1 for b in shared)   # slot 0 still holds
    assert c.active[0]


def test_allocator_eviction_never_reclaims_held_blocks():
    c = cache_of(num_blocks=8)
    t1 = np.arange(16, dtype=np.int32)
    prefilled(c, 0, t1)
    c.free(0)                                         # 4 blocks cached
    t2 = 100 + np.arange(16, dtype=np.int32)
    prefilled(c, 1, t2)                               # 4 fresh: pool full
    held = list(c._owned[1])
    t3 = 200 + np.arange(16, dtype=np.int32)
    c.allocate(2, 16, tokens=t3)                      # must reclaim cached LRU
    assert c.cache_block_evictions == 4
    assert c._owned[1] == held                        # held blocks untouched
    assert all(c._refcount[b] == 1 for b in held)
    assert set(c._owned[2]).isdisjoint(held)
    with pytest.raises(CacheExhausted):               # nothing reclaimable now
        c.allocate(3, 16)


def test_allocator_free_idempotent_and_hardened():
    c = cache_of()
    c.allocate(0, 8)
    bid = c._owned[0][0]
    c.free(0)
    c.free(0)                                         # idempotent no-op
    assert c.free_blocks == 16 and not c.active[0]
    with pytest.raises(ValueError, match="double free"):
        c._release(bid)
    with pytest.raises(ValueError, match="foreign block"):
        c._release(0)                                 # the trash block
    with pytest.raises(ValueError, match="foreign block"):
        c._release(999)
    with pytest.raises(ValueError, match="already allocated"):
        c.allocate(1, 4) or c.allocate(1, 4)
    with pytest.raises(ValueError, match="out of range"):
        c.allocate(7, 4)


def test_allocator_cached_blocks_revive_and_stats():
    c = cache_of()
    t = np.arange(16, dtype=np.int32)
    prefilled(c, 0, t)
    c.free(0)
    s = c.stats()
    assert s["held_blocks"] == 0 and s["cached_blocks"] == 4
    assert s["used_blocks"] == 4                      # cached still uses HBM
    m = c.allocate(1, 16, tokens=t)                   # revive from cache
    assert m == 15
    s = c.stats()
    assert s["prefix_hits"] == 1 and s["prefix_tokens_saved"] == 15
    assert s["held_blocks"] == 4                      # 3 shared + the COW copy
    assert 0.0 <= s["fragmentation"] <= 1.0
    assert s["num_blocks"] == s["free_blocks"] + s["used_blocks"]


def test_allocator_admission_charges_only_uncached_suffix():
    c = cache_of(num_blocks=6, watermark=1)
    t = np.arange(16, dtype=np.int32)                 # 4 blocks
    prefilled(c, 0, t)
    c.free(0)
    # a cold 16-token prompt needs 4 fresh of 6; cached blocks are
    # reclaimable so it fits — but the SAME prompt warm needs just 2
    # (1 COW + 1 suffix), leaving the watermark intact without reclaim
    assert c.blocks_needed(16, tokens=t) == 1         # 3 shared of 4
    assert c.can_admit(16, tokens=t)
    cold = 100 + np.arange(16, dtype=np.int32)
    assert c.blocks_needed(16, tokens=cold) == 4
    # available for a cold prompt counts reclaimable cached blocks
    assert c.available_blocks(tokens=cold) == 2 + 4   # 2 free + 4 cached
    # for the warm prompt the matched chain is excluded from reclaim
    assert c.available_blocks(tokens=t) == 2


def test_resolve_prefix_cache_env_knob(monkeypatch):
    monkeypatch.delenv("DS_PREFIX_CACHE", raising=False)
    assert resolve_prefix_cache(None) is False        # default off
    assert resolve_prefix_cache(True) is True
    monkeypatch.setenv("DS_PREFIX_CACHE", "on")
    assert resolve_prefix_cache(None) is True
    assert resolve_prefix_cache(False) is False       # explicit wins
    monkeypatch.setenv("DS_PREFIX_CACHE", "off")
    assert resolve_prefix_cache(None) is False
    monkeypatch.setenv("DS_PREFIX_CACHE", "sideways")
    with pytest.raises(ValueError, match="DS_PREFIX_CACHE"):
        resolve_prefix_cache(None)


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

SYS = np.arange(1, 25, dtype=np.int32)                # 24-token system prompt


def shared_prompts(n=4, tail=6, seed=0):
    r = np.random.default_rng(seed)
    return [np.concatenate([SYS, r.integers(1, 128, tail).astype(np.int32)])
            for _ in range(n)]


def serve(eng, prompts, prefix_cache, n_new=8, **kw):
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                        prefill_chunk=16, prefix_cache=prefix_cache, **kw)
    out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=n_new)
                   for i, p in enumerate(prompts)])
    return srv, out


def test_serving_warm_vs_cold_token_parity(eng):
    """The acceptance gate: with a shared system prompt the warm path
    reports prefix hits and does FEWER prefill chunks, and every output
    token is identical to the cold (prefix-cache-off) run."""
    prompts = shared_prompts()
    cold, cold_out = serve(eng, prompts, prefix_cache=False)
    warm, warm_out = serve(eng, prompts, prefix_cache=True)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(warm_out[i], cold_out[i])
    assert warm.stats["prefix_hits"] > 0
    assert warm.stats["prefix_tokens_saved"] > 0
    assert warm.stats["prefill_chunks"] < cold.stats["prefill_chunks"]
    assert cold.stats["prefix_hits"] == 0             # off = today's behavior
    # ... and both match the static engine exactly
    refs = _solo_refs(eng, prompts, 8)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(warm_out[i], ref)


def test_serving_cow_divergence_mid_block_parity(eng):
    """Two prompts diverging INSIDE a block: the second request reuses
    the common full blocks, copy-on-writes the divergent one, and still
    matches its solo greedy stream bit-for-bit."""
    base = np.arange(1, 31, dtype=np.int32)           # 30 tokens, bs=8
    div = base.copy()
    div[21] = 99                                      # inside block 2
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                        prefill_chunk=16, prefix_cache=True)
    out1 = srv.run([ServeRequest(rid="a", prompt=base, max_new_tokens=8)])
    out2 = srv.run([ServeRequest(rid="b", prompt=div, max_new_tokens=8)])
    assert srv.cache.cow_copies == 1
    assert srv.stats["prefix_hits"] == 1
    # blocks 0,1 shared + 5 leading tokens of block 2 via the copy
    assert srv.stats["prefix_tokens_saved"] == 2 * 8 + 5
    ref_a, ref_b = _solo_refs(eng, [base, div], 8)
    np.testing.assert_array_equal(out1["a"], ref_a)
    np.testing.assert_array_equal(out2["b"], ref_b)


def test_serving_preempt_requeue_of_sharing_request(eng):
    """A request MAPPING shared blocks can be preempted and resumed:
    free() drops its references (the donor's blocks survive), resume
    re-matches the cache and parity holds."""
    prompts = shared_prompts(n=3, tail=8, seed=3)
    refs = _solo_refs(eng, prompts, 10)
    srv = ServingEngine(eng, num_slots=2, block_size=4, num_blocks=14,
                        prefill_chunk=16, prefix_cache=True)
    srv.cache.watermark = 0
    # warm the index, then run two sharing requests in a pool tight
    # enough that decode growth forces a preemption
    out0 = srv.run([ServeRequest(rid=0, prompt=prompts[0],
                                 max_new_tokens=10)])
    out = srv.run([ServeRequest(rid=1, prompt=prompts[1],
                                max_new_tokens=10),
                   ServeRequest(rid=2, prompt=prompts[2],
                                max_new_tokens=10)])
    assert srv.stats["evictions"] >= 1                # it really preempted
    assert srv.stats["prefix_hits"] >= 2              # they really shared
    np.testing.assert_array_equal(out0[0], refs[0])
    np.testing.assert_array_equal(out[1], refs[1])
    np.testing.assert_array_equal(out[2], refs[2])
    # exactly-once, all done, and no leaked references after drain
    assert all(r.state == "done" for r in srv.finished)
    assert srv.cache.held_blocks == 0


def test_serving_compile_contract_with_prefix_cache(devices):
    """Compile-count contract, prefix cache ON: after warmup the steady
    state compiles NOTHING — admissions with prefix hits, COW copies
    and LRU block reclaim are all host-side or pre-warmed. Each slot
    program (and the COW copy) stays at exactly one executable (fresh
    engine: the strict cache_size pin needs an unshared jit cache)."""
    from deepspeed_tpu.utils.compile_guard import CompileWatch, cache_size
    cfg, params = tiny()
    eng = InferenceEngine(config=cfg, params=params, dtype=jnp.float32)
    base = np.arange(1, 31, dtype=np.int32)
    div = base.copy()
    div[21] = 99
    srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                        prefill_chunk=16, prefix_cache=True)
    srv.run([ServeRequest(rid=0, prompt=base, max_new_tokens=4)])
    watch = CompileWatch(max_compiles=0, label="prefix-cache steady state")
    with watch:
        srv.run([ServeRequest(rid=1, prompt=base, max_new_tokens=4)])
        srv.run([ServeRequest(rid=2, prompt=div, max_new_tokens=4)])
    assert srv.cache.cow_copies >= 1                  # COW ran inside watch
    assert srv.stats["prefix_hits"] >= 2
    # under DS_KV_QUANT=int8 / DS_LORA_SERVE=on the active set is the
    # _q / _l / _ql jit twin family — the per-program count contract is
    # the same (COW copies blocks, not adapters: no _l twin there)
    quant = srv.kv_quant == "int8"
    sfx = ("_q" if quant else "") + ("_l" if srv.lora_serve else "")
    pf = getattr(eng, "_prefill_slot" + sfx)
    dc = getattr(eng, "_decode_slots" + sfx)
    cw = eng._cow_blocks_q if quant else eng._cow_blocks
    n_prefill = cache_size(pf)
    if n_prefill is not None:
        assert n_prefill == 1
        assert cache_size(dc) == 1
        assert cache_size(cw) == 1


def test_serving_env_knob_smoke(eng):
    """gate.sh smoke: prefix_cache=None resolves DS_PREFIX_CACHE from
    the ambient environment; parity vs the static engine must hold
    whichever way the knob points."""
    prompts = shared_prompts(n=2, tail=4, seed=5)
    refs = _solo_refs(eng, prompts, 4)
    srv, out = serve(eng, prompts, prefix_cache=None, n_new=4)
    assert srv.prefix_cache == resolve_prefix_cache(None)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


# ---------------------------------------------------------------------------
# chaos: the new fault sites
# ---------------------------------------------------------------------------

def test_chaos_match_fault_degrades_to_cold_miss(eng):
    """An injected ``cache.match`` exhaustion turns that admission into
    a cold miss: no sharing for THAT request, full parity for all."""
    prompts = shared_prompts(n=3, tail=4, seed=7)
    refs = _solo_refs(eng, prompts, 6)
    with faults_lib.injected(
            Fault("cache.match", "cache_exhausted", step=1), seed=0) as inj:
        srv = ServingEngine(eng, num_slots=1, block_size=8, num_blocks=24,
                            prefill_chunk=16, prefix_cache=True)
        out = srv.run([ServeRequest(rid=i, prompt=p, max_new_tokens=6)
                       for i, p in enumerate(prompts)])
    assert ("cache.match", "cache_exhausted", 1) in inj.fired
    # request 0 cold (nothing cached), request 1 degraded by the fault,
    # request 2 hits — so exactly ONE hit, not two
    assert srv.stats["prefix_hits"] == 1
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


def test_chaos_cow_fault_fails_admission_then_recovers(eng):
    """An injected ``cache.cow`` exhaustion aborts that admission BEFORE
    any bookkeeping mutates (no leaked refcounts); the request retries
    next step, the COW succeeds, and parity holds."""
    base = np.arange(1, 31, dtype=np.int32)
    div = base.copy()
    div[21] = 99
    refs = _solo_refs(eng, [base, div], 6)
    with faults_lib.injected(
            Fault("cache.cow", "cache_exhausted", step=0), seed=0) as inj:
        srv = ServingEngine(eng, num_slots=2, block_size=8, num_blocks=24,
                            prefill_chunk=16, prefix_cache=True)
        out0 = srv.run([ServeRequest(rid=0, prompt=base, max_new_tokens=6)])
        out1 = srv.run([ServeRequest(rid=1, prompt=div, max_new_tokens=6)])
    assert ("cache.cow", "cache_exhausted", 0) in inj.fired
    assert srv.cache.cow_copies == 1                  # the retry copied
    np.testing.assert_array_equal(out0[0], refs[0])
    np.testing.assert_array_equal(out1[1], refs[1])
    # no leaked references: after the drain every refcount is back to 0
    # (the faulted attempt claimed nothing — it fired before bookkeeping)
    assert srv.cache.held_blocks == 0
    assert (srv.cache._refcount == 0).all()
